"""The serving frontend: routing, admission control, cache, completion.

``submit(s, t)`` is the whole online request path:

1. validate the node ids against the graph;
2. consult the :class:`~.cache.ResultCache` — a hit completes
   immediately (``cached=True``), no queue, no batch;
3. route to the target-owner shard (``DistributionController`` — the
   same invariant the campaign partitioner uses: the worker owning the
   TARGET answers);
4. admission control: an OPEN circuit breaker for that shard's worker
   sheds ``UNAVAILABLE``; a full shard queue sheds ``BUSY``. Both are
   immediate — an overloaded frontend answers fast, it never hangs;
5. enqueue with a deadline; the shard's :class:`~.batcher.MicroBatcher`
   forms the batch and this frontend's dispatch callback answers it
   through the configured dispatcher, records the breaker outcome,
   fills the cache, and completes every future.

Every completion stamps the end-to-end latency histogram and (when
tracing is enabled) a ``serve.request`` span, so the online path is
observable from day one like the campaign path.
"""

from __future__ import annotations

import time

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..parallel.partition import DistributionController
from ..transport.wire import RuntimeConfig
from ..utils.log import get_logger
from .batcher import MicroBatcher
from .cache import ResultCache, knob_fingerprint
from .config import ServeConfig
from .queue import ShardQueue
from .request import (
    BUSY, ERROR, Future, OK, ServeRequest, ServeResult, TIMEOUT,
    UNAVAILABLE,
)

log = get_logger(__name__)

M_REQS = obs_metrics.counter(
    "serve_requests_total", "requests submitted to the frontend")
M_OK = obs_metrics.counter(
    "serve_requests_ok_total", "requests answered OK (cache or shard)")
M_BUSY = obs_metrics.counter(
    "serve_shed_busy_total", "requests shed BUSY: shard queue full")
M_UNAVAIL = obs_metrics.counter(
    "serve_shed_unavailable_total",
    "requests shed UNAVAILABLE: open breaker or shutdown")
M_TIMEOUTS = obs_metrics.counter(
    "serve_timeouts_total", "requests expired before dispatch")
M_ERRORS = obs_metrics.counter(
    "serve_errors_total", "requests failed by dispatch errors")
H_E2E = obs_metrics.histogram(
    "serve_request_seconds",
    "submit -> completion, end to end (cache hits included)")


class ServingFrontend:
    """One process's online oracle service over a set of shards.

    ``registry``/``breaker_key`` wire in the head-side circuit breakers
    (``transport.resilience``): ``breaker_key(wid)`` must return the
    same key the campaign path uses (``(host, wid)``) so breakers — and
    their background healing probes — are shared infrastructure, not a
    serving fork. The caller owns the registry's lifecycle
    (``registry.shutdown()``)."""

    def __init__(self, dc: DistributionController, dispatcher,
                 sconf: ServeConfig | None = None,
                 rconf: RuntimeConfig | None = None,
                 diff: str = "-", registry=None, breaker_key=None):
        self.dc = dc
        self.dispatcher = dispatcher
        self.sconf = sconf or ServeConfig.from_env()
        self.rconf = rconf or RuntimeConfig()
        self.diff = diff
        self.registry = registry
        self._breaker_key = breaker_key or (lambda wid: wid)
        self._fp = knob_fingerprint(self.rconf)
        self.cache = ResultCache(self.sconf.cache_bytes)
        self._queues: dict[int, ShardQueue] = {}
        self._batchers: dict[int, MicroBatcher] = {}
        for wid in range(dc.maxworker):
            q = ShardQueue(self.sconf.queue_depth)
            self._queues[wid] = q
            self._batchers[wid] = MicroBatcher(
                wid, q,
                (lambda batch, _wid=wid:
                 self._dispatch_batch(_wid, batch)),
                max_batch=self.sconf.max_batch,
                max_wait_s=self.sconf.max_wait_s)
        self._started = False
        self._closed = False

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ServingFrontend":
        if not self._started:
            for b in self._batchers.values():
                b.start()
            self._started = True
            log.info("serving frontend up: %d shard(s), max_batch=%d, "
                     "max_wait=%.1fms, queue_depth=%d, cache=%dMB",
                     self.dc.maxworker, self.sconf.max_batch,
                     self.sconf.max_wait_ms, self.sconf.queue_depth,
                     self.sconf.cache_bytes >> 20)
        return self

    def stop(self, drain_s: float = 5.0) -> None:
        """Shed new requests, drain admitted ones (bounded), join the
        batcher threads. ``drain_s`` is ONE shared budget across all
        shards (queues close up front, shards drain concurrently), not
        a per-shard allowance — shutdown latency stays ~drain_s even
        with many busy shards. Idempotent."""
        self._closed = True
        if self._started:
            for q in self._queues.values():
                q.close()
            deadline = time.monotonic() + max(drain_s, 0.0)
            for b in self._batchers.values():
                b.stop(drain_s=max(0.0, deadline - time.monotonic()))
            self._started = False
        close = getattr(self.dispatcher, "close", None)
        if close is not None:
            close()

    # ------------------------------------------------------------- submit
    def submit(self, s: int, t: int) -> Future:
        M_REQS.inc()
        now = time.monotonic()
        if self._closed or not self._started:
            M_UNAVAIL.inc()
            return self._immediate(ServeResult(
                UNAVAILABLE, int(s), int(t), detail="not-serving"), now)
        s, t = int(s), int(t)
        if not (0 <= s < self.dc.nodenum and 0 <= t < self.dc.nodenum):
            M_ERRORS.inc()
            return self._immediate(ServeResult(
                ERROR, s, t, detail="node-out-of-range"), now)
        key = (s, t, self.diff, self._fp)
        hit = self.cache.get(key)
        if hit is not None:
            cost, plen, fin = hit
            M_OK.inc()
            return self._immediate(ServeResult(
                OK, s, t, cost=cost, plen=plen, finished=fin,
                cached=True), now)
        wid = int(self.dc.worker_of(t))   # scalar index, no per-request
        # array allocation on the admission hot path
        if (self.registry is not None
                and not self.registry.allow(self._breaker_key(wid))):
            M_UNAVAIL.inc()
            return self._immediate(ServeResult(
                UNAVAILABLE, s, t, detail="circuit-open"), now)
        req = ServeRequest(s=s, t=t, wid=wid, key=key, t_submit=now,
                           deadline=now + self.sconf.deadline_s)
        if not self._queues[wid].try_put(req):
            if self._queues[wid].closed:
                # stop() raced this submit past the _closed check: the
                # shed is a shutdown, not overload — label it so
                M_UNAVAIL.inc()
                return self._immediate(ServeResult(
                    UNAVAILABLE, s, t, detail="not-serving"), now)
            M_BUSY.inc()
            return self._immediate(ServeResult(
                BUSY, s, t, detail="queue-full"), now)
        return req.future

    def query(self, s: int, t: int,
              timeout: float | None = None) -> ServeResult:
        """Blocking convenience: submit and wait. The default timeout is
        the request deadline plus dispatch headroom — a broken shard
        still yields a terminal result, never a wedged caller."""
        if timeout is None:
            timeout = self.sconf.deadline_s + 30.0
        return self.submit(s, t).result(timeout)

    def set_diff(self, diff: str) -> None:
        """Switch the active congestion diff. The cache is invalidated
        wholesale: keys carry the diff so stale entries could never be
        *served*, but a diff path can be rewritten in place and the
        memory is better spent on the new round's traffic."""
        if diff != self.diff:
            n = self.cache.invalidate()
            log.info("diff change %s -> %s: %d cache entries dropped",
                     self.diff, diff, n)
            self.diff = diff

    # --------------------------------------------------------- completion
    def _immediate(self, res: ServeResult, t_submit: float) -> Future:
        res.t_done = time.monotonic()
        # only served requests (cache hits) land in the latency
        # histogram: near-zero BUSY/UNAVAILABLE shed samples would make
        # p50/p99 IMPROVE exactly when the service is overloaded
        if res.status == OK:
            H_E2E.observe(res.t_done - t_submit)
        return Future.completed(res)

    def _finish(self, req: ServeRequest, res: ServeResult) -> None:
        res.t_done = time.monotonic()
        H_E2E.observe(res.t_done - req.t_submit)
        obs_trace.add_span("serve.request", res.t_done - req.t_submit,
                           wid=req.wid, status=res.status)
        req.future.set(res)

    def _dispatch_batch(self, wid: int, batch: list[ServeRequest]) -> None:
        """MicroBatcher callback: expire, answer, record, fill, finish."""
        now = time.monotonic()
        live = []
        for r in batch:
            if r.expired(now):
                M_TIMEOUTS.inc()
                self._finish(r, ServeResult(TIMEOUT, r.s, r.t,
                                            detail="deadline"))
            else:
                live.append(r)
        if not live:
            return
        queries = np.asarray([[r.s, r.t] for r in live], np.int64)
        key = self._breaker_key(wid)
        # pin the diff actually dispatched: a set_diff racing this batch
        # must not let answers computed under the NEW diff be cached
        # under requests' submit-time (old-diff) keys
        diff = self.diff
        err = ""
        try:
            with obs_trace.span("serve.dispatch", wid=wid,
                                size=len(live)):
                cost, plen, fin = self.dispatcher.answer_batch(
                    wid, queries, self.rconf, diff)
            ok = True
        except Exception as e:  # noqa: BLE001 — any dispatch failure
            # becomes per-request ERROR + a breaker failure record
            log.exception("shard w%d serving batch failed: %s", wid, e)
            ok = False
            err = f"{type(e).__name__}: {e}"
        if self.registry is not None:
            self.registry.record(key, ok)
        if not ok:
            for r in live:
                M_ERRORS.inc()
                self._finish(r, ServeResult(ERROR, r.s, r.t, detail=err))
            return
        for i, r in enumerate(live):
            val = (int(cost[i]), int(plen[i]), bool(fin[i]))
            if r.key[2] == diff:
                self.cache.put(r.key, val)
            M_OK.inc()
            self._finish(r, ServeResult(OK, r.s, r.t, cost=val[0],
                                        plen=val[1], finished=val[2]))
