"""The serving frontend: routing, admission control, cache, completion.

``submit(s, t)`` is the whole online request path:

1. validate the node ids against the graph;
2. consult the :class:`~.cache.ResultCache` — a hit completes
   immediately (``cached=True``), no queue, no batch;
3. route to the target-owner shard (``DistributionController`` — the
   same invariant the campaign partitioner uses: the worker owning the
   TARGET answers);
4. admission control: an OPEN circuit breaker for that shard's worker
   sheds ``UNAVAILABLE``; a full shard queue sheds ``BUSY``. Both are
   immediate — an overloaded frontend answers fast, it never hangs;
5. enqueue with a deadline; the shard's :class:`~.batcher.MicroBatcher`
   forms the batch and this frontend's dispatch callback answers it
   through the configured dispatcher, records the breaker outcome,
   fills the cache, and completes every future.

Every completion stamps the end-to-end latency histogram and (when
tracing is enabled) a ``serve.request`` span, so the online path is
observable from day one like the campaign path.
"""

from __future__ import annotations

import dataclasses
import queue as _stdqueue
import threading
import time

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import quantiles as obs_quantiles
from ..obs import recorder as obs_recorder
from ..obs import trace as obs_trace
from ..parallel.partition import DistributionController
from ..transport import resilience
from ..transport.wire import RuntimeConfig
from ..utils.log import get_logger
from .batcher import MicroBatcher
from .cache import ResultCache, knob_fingerprint
from .config import ServeConfig
from .hedge import HedgeConfig, HedgeTracker, M_BUDGET_DENIED, M_WON
from .queue import ShardQueue
from .request import (
    BUSY, ERROR, Future, OK, ServeRequest, ServeResult, TIMEOUT,
    UNAVAILABLE,
)

log = get_logger(__name__)

M_REQS = obs_metrics.counter(
    "serve_requests_total", "requests submitted to the frontend")
M_OK = obs_metrics.counter(
    "serve_requests_ok_total", "requests answered OK (cache or shard)")
M_BUSY = obs_metrics.counter(
    "serve_shed_busy_total", "requests shed BUSY: shard queue full")
M_UNAVAIL = obs_metrics.counter(
    "serve_shed_unavailable_total",
    "requests shed UNAVAILABLE: open breaker or shutdown")
M_TIMEOUTS = obs_metrics.counter(
    "serve_timeouts_total", "requests expired before dispatch")
M_ERRORS = obs_metrics.counter(
    "serve_errors_total", "requests failed by dispatch errors")
H_E2E = obs_metrics.histogram(
    "serve_request_seconds",
    "submit -> completion, end to end (cache hits included)")


class ServingFrontend:
    """One process's online oracle service over a set of shards.

    ``registry``/``breaker_key`` wire in the head-side circuit breakers
    (``transport.resilience``): ``breaker_key(wid)`` must return the
    same key the campaign path uses (``(host, wid)``) so breakers — and
    their background healing probes — are shared infrastructure, not a
    serving fork. The caller owns the registry's lifecycle
    (``registry.shutdown()``)."""

    def __init__(self, dc: DistributionController, dispatcher,
                 sconf: ServeConfig | None = None,
                 rconf: RuntimeConfig | None = None,
                 diff: str = "-", registry=None, breaker_key=None,
                 hconf: HedgeConfig | None = None, membership=None,
                 traffic=None):
        self.dc = dc
        self.dispatcher = dispatcher
        #: live-traffic hook (``traffic.epochs.DiffEpochManager`` or
        #: anything with ``refresh()``/``active()``/``statusz()`` and
        #: the ``poll_s``/``scoped_max``/``sig_moves`` knobs): when set,
        #: a pump thread polls the segment stream and swaps the active
        #: fused diff on the serve path WITHOUT restart — in-flight
        #: batches pinned the old fused file at dispatch and finish on
        #: the old epoch; the cache invalidates scoped to the swap's
        #: affected edges. None = the static-diff world, byte-for-byte
        #: the pre-traffic behavior (diff epoch stays 0 everywhere).
        self.traffic = traffic
        #: elastic-membership hook (``parallel.membership
        #: .MembershipController`` or anything with ``epoch``,
        #: ``candidates_for(shard)`` and ``statusz()``): when set, each
        #: batch's candidate chain comes from the LIVE assignment —
        #: during a migration window that is the dual-read order (old
        #: owner authoritative, adopter second) — and the committed
        #: epoch is stamped on the wire. None = the controller's static
        #: chain, byte-for-byte the pre-elastic behavior.
        self.membership = membership
        self.sconf = sconf or ServeConfig.from_env()
        self.rconf = rconf or RuntimeConfig()
        self.diff = diff
        #: active diff epoch (0 = static diff). Published AFTER
        #: ``_diff_epoch`` on a swap; a torn read at worst builds a key
        #: that matches nothing — a cache miss, never a wrong hit.
        self._diff_epoch = 0
        self._sig_k = 0
        #: the fused difffile the SWAP path last published — scoped
        #: invalidation matches survivors against this, NOT self.diff,
        #: which a manual set_diff() can point at an unrelated file
        #: whose entries were never computed under any fusion
        self._fused_diff = self.diff
        if traffic is not None:
            # catch up to the stream before serving: a frontend started
            # mid-campaign begins at the newest fused epoch instead of
            # replaying the whole history one swap at a time
            traffic.refresh()
            self._diff_epoch, self.diff, _ = traffic.active()
            self._fused_diff = self.diff
            self._sig_k = max(int(traffic.sig_moves), 0)
        self._traffic_stop = threading.Event()
        self._traffic_thread: threading.Thread | None = None
        self.registry = registry
        self._breaker_key = breaker_key or (lambda wid: wid)
        self._fp = knob_fingerprint(self.rconf)
        #: DOS_ANSWER_FP rides the rconf: when set, the dispatcher
        #: verifies reply fingerprints AND the cache re-checks stored
        #: entry fingerprints on every hit (integrity plane)
        self.cache = ResultCache(
            self.sconf.cache_bytes,
            fingerprint=getattr(self.rconf, "answer_fp", False))
        #: answer-integrity hooks (``integrity`` package), attached by
        #: the serve CLI when the DOS_AUDIT_*/DOS_SCRUB_* knobs enable
        #: them; None = byte-identical legacy behavior
        self.auditor = None
        self.scrubber = None
        #: hedged dispatch (replicated shards only): per-shard latency
        #: quantiles drive the duplicate-request delay, a rate budget
        #: bounds the duplicates
        self.hedge = HedgeTracker(hconf or HedgeConfig.from_env())
        #: typed query families currently shed by the control plane's
        #: brownout ladder (empty = everything admitted). Read by
        #: ``traffic.families.QueryFamilies`` before submit; plain s-t
        #: queries are never in this set.
        self.shed_families: frozenset = frozenset()
        self._queues: dict[int, ShardQueue] = {}
        self._batchers: dict[int, MicroBatcher] = {}
        for wid in range(dc.maxworker):
            q = ShardQueue(
                self.sconf.queue_depth,
                gauge=obs_metrics.gauge(
                    f"serve_queue_depth_w{wid}",
                    f"requests queued on shard {wid}'s queue (its "
                    "primary's lane; failover/hedges drain it via "
                    "replicas)") if dc.replication > 1 else None)
            self._queues[wid] = q
            self._batchers[wid] = MicroBatcher(
                wid, q,
                (lambda batch, _wid=wid:
                 self._dispatch_batch(_wid, batch)),
                max_batch=self.sconf.max_batch,
                max_wait_s=self.sconf.max_wait_s)
        self._started = False
        self._closed = False

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ServingFrontend":
        if not self._started:
            for b in self._batchers.values():
                b.start()
            self._started = True
            if self.traffic is not None:
                self._traffic_stop.clear()
                self._traffic_thread = threading.Thread(
                    target=self._traffic_loop, daemon=True,
                    name="dos-serve-traffic")
                self._traffic_thread.start()
            log.info("serving frontend up: %d shard(s), max_batch=%d, "
                     "max_wait=%.1fms, queue_depth=%d, cache=%dMB",
                     self.dc.maxworker, self.sconf.max_batch,
                     self.sconf.max_wait_ms, self.sconf.queue_depth,
                     self.sconf.cache_bytes >> 20)
        return self

    def stop(self, drain_s: float = 5.0) -> None:
        """Shed new requests, drain admitted ones (bounded), join the
        batcher threads. ``drain_s`` is ONE shared budget across all
        shards (queues close up front, shards drain concurrently), not
        a per-shard allowance — shutdown latency stays ~drain_s even
        with many busy shards. Idempotent."""
        self._closed = True
        # stop the epoch pump FIRST: a swap landing mid-drain would
        # re-key the cache under batches that will never complete
        self._traffic_stop.set()
        if self._traffic_thread is not None:
            self._traffic_thread.join(timeout=5.0)
            self._traffic_thread = None
        if self._started:
            for q in self._queues.values():
                q.close()
            deadline = time.monotonic() + max(drain_s, 0.0)
            for b in self._batchers.values():
                b.stop(drain_s=max(0.0, deadline - time.monotonic()))
            self._started = False
        close = getattr(self.dispatcher, "close", None)
        if close is not None:
            close()

    # ------------------------------------------------------------- submit
    def submit(self, s: int, t: int) -> Future:
        M_REQS.inc()
        now = time.monotonic()
        if self._closed or not self._started:
            M_UNAVAIL.inc()
            return self._immediate(ServeResult(
                UNAVAILABLE, int(s), int(t), detail="not-serving"), now)
        s, t = int(s), int(t)
        if not (0 <= s < self.dc.nodenum and 0 <= t < self.dc.nodenum):
            M_ERRORS.inc()
            return self._immediate(ServeResult(
                ERROR, s, t, detail="node-out-of-range"), now)
        # both epochs are in the key: a post-reshard hit must never
        # serve a result computed by a worker that no longer owns the
        # shard, and a post-swap hit must never serve an old fusion's
        # cost (scoped invalidation RE-KEYS provably-safe entries, so
        # survivors keep hitting)
        key = (s, t, self.diff, self._fp, self._membership_epoch(),
               int(self._diff_epoch))
        hit = self.cache.get(key)
        if hit is not None:
            cost, plen, fin = hit
            M_OK.inc()
            return self._immediate(ServeResult(
                OK, s, t, cost=cost, plen=plen, finished=fin,
                cached=True), now)
        wid = int(self.dc.worker_of(t))   # scalar index, no per-request
        # array allocation on the admission hot path
        if self.registry is not None:
            cands = self._candidates(wid)
            if len(cands) == 1:
                # single candidate: the pre-replication admission path,
                # byte for byte (allow() keeps its trial semantics);
                # the breaker belongs to the shard's LIVE owner — the
                # shard id itself until a membership epoch moves it
                # (self._candidates reads the live view, so an epoch
                # committed mid-serve re-keys admission too)
                if not self.registry.allow(
                        self._breaker_key(cands[0])):
                    M_UNAVAIL.inc()
                    return self._immediate(ServeResult(
                        UNAVAILABLE, s, t, detail="circuit-open"), now)
            elif not any(
                    self.registry.available(self._breaker_key(c))
                    for c in cands):
                # every candidate (replica chain, plus the adopter when
                # a dual-read window is open — >1 candidates can happen
                # even at R=1) is breaker-dead: shed NOW — queueing
                # would only turn a fast explicit answer into a
                # deadline'd hang
                M_UNAVAIL.inc()
                return self._immediate(ServeResult(
                    UNAVAILABLE, s, t, detail="no-live-replica"), now)
        req = ServeRequest(s=s, t=t, wid=wid, key=key, t_submit=now,
                           deadline=now + self.sconf.deadline_s)
        if not self._queues[wid].try_put(req):
            if self._queues[wid].closed:
                # stop() raced this submit past the _closed check: the
                # shed is a shutdown, not overload — label it so
                M_UNAVAIL.inc()
                return self._immediate(ServeResult(
                    UNAVAILABLE, s, t, detail="not-serving"), now)
            M_BUSY.inc()
            return self._immediate(ServeResult(
                BUSY, s, t, detail="queue-full"), now)
        return req.future

    def query(self, s: int, t: int,
              timeout: float | None = None) -> ServeResult:
        """Blocking convenience: submit and wait. The default timeout is
        the request deadline plus dispatch headroom — a broken shard
        still yields a terminal result, never a wedged caller."""
        if timeout is None:
            timeout = self.sconf.deadline_s + 30.0
        return self.submit(s, t).result(timeout)

    # --------------------------------------------------- brownout hooks
    # Mutators for the control plane's brownout ladder. Both configs
    # are frozen dataclasses, so each step swaps in a fresh immutable
    # snapshot (``dataclasses.replace``) rather than mutating shared
    # state under readers — a dispatch thread mid-request sees either
    # the old config or the new one, never a torn mix.
    def set_hedge_budget(self, budget: float) -> None:
        self.hedge.config = dataclasses.replace(
            self.hedge.config, budget=float(budget))

    def set_deadline_ms(self, ms: float) -> None:
        """Applies to requests admitted from now on; in-flight requests
        keep the absolute deadline stamped at submit."""
        self.sconf = dataclasses.replace(self.sconf, deadline_ms=float(ms))

    def set_family_shed(self, kinds) -> None:
        self.shed_families = frozenset(kinds)

    # ------------------------------------------------------------ statusz
    def statusz(self) -> dict:
        """Live serving state for the ``/statusz`` endpoint
        (``obs.http``): per-shard queue depths and replica/failover
        chains, breaker states, hedge rate + per-shard hedge delays,
        cache occupancy — the "which replica is absorbing failover"
        page a fleet operator reads first."""
        shards = {}
        for wid, q in self._queues.items():
            shards[str(wid)] = {
                "queue_depth": len(q),
                "queue_bound": q.depth,
                "closed": q.closed,
                # the LIVE candidate chain dispatch actually walks
                # (dual-read order during a migration window) — the
                # static construction-time chain would name the wrong
                # workers during exactly the incidents this page is for
                "replicas": [int(c) for c in self._candidates(wid)],
                "hedge_delay_ms": round(
                    self.hedge.delay_s(wid) * 1e3, 3),
            }
        out = {
            "serving": self._started and not self._closed,
            "diff": self.diff,
            "diff_epoch": int(self._diff_epoch),
            "replication": int(self.dc.replication),
            "epoch": int(self.membership.epoch
                         if self.membership is not None
                         else self.dc.epoch),
            "shards": shards,
            "hedge": {
                "enabled": self.hedge.config.enabled,
                "rate": round(self.hedge.hedge_rate(), 4),
                "budget": self.hedge.config.budget,
            },
            "cache": {
                "entries": len(self.cache),
                "max_bytes": self.cache.max_bytes,
            },
        }
        if self.shed_families:
            # only under an active brownout — the legacy statusz body
            # stays byte-identical when the control plane is off
            out["shed_families"] = sorted(self.shed_families)
        # integrity plane — sections appear only when a knob enabled
        # them (legacy statusz body unchanged otherwise)
        if self.cache.fingerprint:
            out["cache"]["fp_mismatches"] = self.cache.fp_mismatches
        if self.auditor is not None:
            out["audit"] = self.auditor.statusz()
        if self.scrubber is not None:
            out["scrub"] = self.scrubber.statusz()
        # worker mesh shape (DOS_MESH_DEVICES resolution) — reported
        # best-effort: a head whose backend cannot resolve devices
        # (host-wire frontend with no local accelerator runtime) shows
        # the single-device default rather than erroring the page
        try:
            from ..parallel.mesh import mesh_devices
            out["mesh"] = {"devices": int(mesh_devices()),
                           "axis": "lane"}
        except Exception as e:  # noqa: BLE001 — statusz must render;
            # the mesh cell degrades to absent (blank in `dos-obs top`)
            log.debug("mesh shape unavailable for statusz: %s", e)
        if self.membership is not None:
            mstat = self.membership.statusz()
            if "migration" in mstat:
                out["migration"] = mstat["migration"]
        if self.traffic is not None:
            out["traffic"] = self.traffic.statusz()
        if self.registry is not None:
            out["breakers"] = self.registry.statusz()
        # streaming-transport connection table (RPC/auto dispatchers):
        # per-worker persistent-socket state — connected, in-flight
        # frames, credit window. Absent for engine/FIFO backends;
        # `dos-obs top` renders blanks for the missing section
        tstat = getattr(self.dispatcher, "statusz", None)
        if tstat is not None:
            try:
                out["transport"] = tstat()
            except Exception as e:  # noqa: BLE001 — statusz must
                # render even when a dispatcher lane is mid-teardown
                log.debug("transport statusz unavailable: %s", e)
        return out

    def _membership_epoch(self) -> int:
        return int(self.membership.epoch if self.membership is not None
                   else self.dc.epoch)

    # ------------------------------------------------------ live traffic
    def _traffic_loop(self) -> None:
        """Epoch pump: poll the segment stream, swap on new epochs.
        Never dies — a failing poll keeps serving the current epoch."""
        while not self._traffic_stop.wait(self.traffic.poll_s):
            try:
                self.poll_traffic()
            except Exception as e:  # noqa: BLE001 — the pump outlives
                # any single bad segment batch
                log.exception("traffic epoch pump failed: %s", e)

    def poll_traffic(self) -> bool:
        """One pump step (also callable inline from tests/tools):
        returns True iff a new epoch was applied."""
        if self.traffic is None or not self.traffic.refresh():
            return False
        self._apply_swap()
        return True

    def _apply_swap(self) -> None:
        epoch, difffile, affected = self.traffic.active()
        if epoch == self._diff_epoch and difffile == self.diff:
            return
        old_epoch = self._diff_epoch
        # survivors must have been computed under the previous FUSION:
        # self.diff can be a manual set_diff() target whose entries the
        # swap's affected set says nothing about
        old_diff = self._fused_diff
        # epoch first, then diff: a torn read pairs the OLD diff with
        # the NEW epoch — a key that matches nothing (miss), never a
        # wrong hit; the caching guard in _dispatch_live pins both
        self._diff_epoch = epoch
        self.diff = difffile
        self._fused_diff = difffile
        dropped, kept, reason = self.cache.invalidate_scoped(
            affected, difffile, epoch,
            max_edges=self.traffic.scoped_max,
            old_diff=old_diff, old_depoch=old_epoch)
        log.info("diff epoch %d -> %d live swap: %d cache entries "
                 "dropped (%s), %d re-keyed survivors, %d edge(s) "
                 "affected", old_epoch, epoch, dropped, reason, kept,
                 len(affected))
        obs_recorder.emit("epoch_swap", old=old_epoch, new=epoch,
                          dropped=dropped, kept=kept)

    def set_diff(self, diff: str) -> None:
        """Switch the active congestion diff. The cache is invalidated
        wholesale: keys carry the diff so stale entries could never be
        *served*, but a diff path can be rewritten in place and the
        memory is better spent on the new round's traffic."""
        if diff != self.diff:
            n = self.cache.invalidate()
            log.info("diff change %s -> %s: %d cache entries dropped",
                     self.diff, diff, n)
            self.diff = diff

    def _candidates(self, wid: int) -> list[int]:
        """The shard's candidate chain from the LIVE assignment when a
        membership hook is wired (dual-read windows, epoch commits made
        by other processes), else the controller's static chain —
        byte-for-byte the pre-elastic behavior."""
        if self.membership is not None:
            return self.membership.candidates_for(wid)
        return self.dc.replica_workers(wid)

    # --------------------------------------------------------- completion
    def _immediate(self, res: ServeResult, t_submit: float) -> Future:
        res.t_done = time.monotonic()
        # only served requests (cache hits) land in the latency
        # histogram: near-zero BUSY/UNAVAILABLE shed samples would make
        # p50/p99 IMPROVE exactly when the service is overloaded
        if res.status == OK:
            H_E2E.observe(res.t_done - t_submit)
            obs_quantiles.observe("serve_request_seconds",
                                  res.t_done - t_submit)
        return Future.completed(res)

    def _finish(self, req: ServeRequest, res: ServeResult) -> None:
        res.t_done = time.monotonic()
        e2e = res.t_done - req.t_submit
        H_E2E.observe(e2e)
        # live sliding-window quantiles with an exemplar: the window's
        # worst request keeps the trace id its batch dispatched under,
        # so a bad p99 on the scrape links straight to its Perfetto
        # timeline
        obs_quantiles.observe("serve_request_seconds", e2e,
                              trace_id=req.trace_id)
        obs_trace.add_span("serve.request", e2e, wid=req.wid,
                           status=res.status,
                           **({"trace_id": req.trace_id}
                              if req.trace_id else {}))
        req.future.set(res)

    def _dispatch_batch(self, wid: int, batch: list[ServeRequest]) -> None:
        """MicroBatcher callback: expire, answer, record, fill, finish."""
        now = time.monotonic()
        live = []
        for r in batch:
            if r.expired(now):
                M_TIMEOUTS.inc()
                self._finish(r, ServeResult(TIMEOUT, r.s, r.t,
                                            detail="deadline"))
            else:
                live.append(r)
        if not live:
            return
        # with tracing on, every batch gets its own trace id: it rides
        # the wire (RuntimeConfig extension) so the worker ships its
        # spans back under it, it stamps each request (the quantile
        # exemplar key), and it tags this thread's log records — scoped
        # to this batch (the runner thread persists; a leaked id would
        # mislabel between-batch log records with the PREVIOUS batch)
        if obs_trace.enabled():
            tid = obs_trace.new_trace_id()
            obs_trace.set_trace_id(tid)
            for r in live:
                r.trace_id = tid
            try:
                self._dispatch_live(wid, live)
            finally:
                obs_trace.set_trace_id(None)
        else:
            self._dispatch_live(wid, live)

    def _dispatch_live(self, wid: int, live: list[ServeRequest]) -> None:
        queries = np.asarray([[r.s, r.t] for r in live], np.int64)
        # pin the (diff, diff epoch) actually dispatched: a set_diff or
        # epoch swap racing this batch must not let answers computed
        # under the NEW fusion be cached under requests' submit-time
        # (old-epoch) keys — and vice versa
        diff = self.diff
        depoch = int(self._diff_epoch)
        err = ""
        ok = False
        cost = plen = fin = None
        sigs = None
        candidates = self._candidates(wid)
        attempted = False
        failed_over = False
        for via in candidates:
            key = self._breaker_key(via)
            if (len(candidates) > 1 and self.registry is not None
                    and not self.registry.allow(key)):
                # dead replica: skip without a dispatch (R=1 keeps the
                # admission-time breaker semantics — no second gate)
                continue
            if attempted or via != candidates[0]:
                if not failed_over:
                    failed_over = True
                    resilience.M_FAILOVER.inc()
                log.warning("shard w%d batch failing over to replica "
                            "host w%d", wid, via)
            attempted = True
            try:
                cost, plen, fin, sigs = self._dispatch_hedged(
                    wid, via, candidates, queries, diff,
                    depoch=depoch, tid=live[0].trace_id)
                ok = True
            except Exception as e:  # noqa: BLE001 — any dispatch
                # failure becomes a breaker failure record (booked by
                # the attempt itself, see _dispatch_hedged) + (once the
                # chain is exhausted) per-request ERROR
                log.exception("shard w%d serving batch via w%d "
                              "failed: %s", wid, via, e)
                err = f"{type(e).__name__}: {e}"
            if ok:
                break
        if not ok:
            if not attempted:
                # every replica's breaker was open at dispatch time
                # (they half-opened away again since admission): shed
                # rather than hang — the admission guarantee holds at
                # dispatch too
                for r in live:
                    M_UNAVAIL.inc()
                    self._finish(r, ServeResult(
                        UNAVAILABLE, r.s, r.t, detail="no-live-replica"))
                return
            for r in live:
                M_ERRORS.inc()
                self._finish(r, ServeResult(ERROR, r.s, r.t, detail=err))
            return
        if self.auditor is not None:
            # OFF the reply path: the clients' answers complete below
            # regardless; the sampled dual execution decides whether to
            # keep trusting this engine (integrity.audit)
            self.auditor.maybe_submit(wid, via, candidates, queries,
                                      self.rconf, diff, cost, plen, fin)
        for i, r in enumerate(live):
            val = (int(cost[i]), int(plen[i]), bool(fin[i]))
            if (r.key[2] == diff
                    and (len(r.key) <= 5 or r.key[5] == depoch)):
                self.cache.put(r.key, val,
                               sig=sigs[i] if sigs is not None
                               else None)
            M_OK.inc()
            self._finish(r, ServeResult(OK, r.s, r.t, cost=val[0],
                                        plen=val[1], finished=val[2]))

    # ------------------------------------------------- hedged dispatch
    def _answer_once(self, wid: int, via: int, queries, diff: str,
                     depoch: int = 0, tid: str = ""):
        """One dispatch lane; returns ``(cost, plen, fin, sigs)`` where
        ``sigs`` is a per-query path-signature list (or None when no
        signatures were captured). ``tid`` is the batch's trace id: it
        tags this thread (hedge lanes run on fresh threads that would
        otherwise be untagged), rides the wire so a FIFO worker captures
        its spans under it, and labels the dispatch span."""
        rconf = self.rconf
        epoch = (self.membership.epoch if self.membership is not None
                 else self.dc.epoch)
        if epoch and not rconf.epoch:
            # the wire carries the table version the routing decision
            # was made under (elastic-membership wire extension)
            rconf = dataclasses.replace(rconf, epoch=epoch)
        if depoch and not rconf.diff_epoch:
            # the traffic twin: the diff epoch this batch's fused file
            # was pinned at (tolerate-older / gate-newer on the worker)
            rconf = dataclasses.replace(rconf, diff_epoch=int(depoch))
        if tid:
            obs_trace.set_trace_id(tid)
            if not rconf.trace_id:
                rconf = dataclasses.replace(rconf, trace_id=tid)
        want_sigs = (self._sig_k > 0 and self.cache.enabled
                     and hasattr(self.dispatcher,
                                 "answer_batch_paths"))
        with obs_trace.span("serve.dispatch", wid=via, shard=wid,
                            size=len(queries)):
            if want_sigs:
                rconf = dataclasses.replace(rconf, sig_k=self._sig_k)
                cost, plen, fin, nodes, moves = (
                    self.dispatcher.answer_batch_paths(
                        wid, queries, rconf, diff, via=via))
                return cost, plen, fin, self._build_sigs(
                    plen, nodes, moves)
            cost, plen, fin = self.dispatcher.answer_batch(
                wid, queries, rconf, diff, via=via)
            return cost, plen, fin, None

    def _build_sigs(self, plen, nodes, moves):
        """Per-query path signatures: the walked node set, or None when
        the capture is INCOMPLETE (path longer than ``sig_k`` — such an
        entry must invalidate conservatively on every swap)."""
        if nodes is None or moves is None:
            return None
        if len(nodes) != len(plen) or len(moves) != len(plen):
            # not this batch's capture (defense in depth next to the
            # dispatcher's lane lock): no signatures beats wrong ones
            return None
        sigs = []
        for i in range(len(plen)):
            if int(moves[i]) == int(plen[i]):
                sigs.append(frozenset(
                    int(x) for x in nodes[i, :int(moves[i]) + 1]))
            else:
                sigs.append(None)
        return sigs

    def _hedge_target(self, wid: int, via: int, candidates) -> int | None:
        """The replica a hedge would duplicate to: the first candidate
        other than ``via`` whose breaker looks live (read-only check —
        a duplicate must not consume half-open trial slots)."""
        for c in candidates:
            if c == via:
                continue
            if (self.registry is None
                    or self.registry.available(self._breaker_key(c))):
                return c
        return None

    def _record(self, target: int, ok: bool) -> None:
        if self.registry is not None:
            self.registry.record(self._breaker_key(target), ok)

    def _dispatch_hedged(self, wid: int, via: int, candidates,
                         queries, diff: str, depoch: int = 0,
                         tid: str = ""):
        """One batch through ``via``, hedged: if no answer lands within
        the shard's adaptive delay (recent latency quantile, floor
        ``DOS_HEDGE_MIN_MS``) and the hedge budget grants, a duplicate
        goes to a live replica — first answer wins, the loser's result
        is discarded (identical rows, deterministic kernels: redundant,
        never wrong). Raises only when every issued attempt raised.

        Breaker accounting happens PER LANE, by the attempt itself, at
        the moment that attempt completes — a hedge win must not book a
        success on the primary's breaker (a wedged primary would then
        never OPEN and budget-denied batches would keep hanging on it);
        a loser that eventually times out records its own failure from
        its background thread."""
        alt = None
        if self.hedge.config.enabled and len(candidates) > 1:
            if self.hedge.would_issue():
                alt = self._hedge_target(wid, via, candidates)
            else:
                # budget spent: this batch could never hedge — book the
                # denial and stay on the cheap inline path
                M_BUDGET_DENIED.inc()
        if alt is None:
            # unreplicated / hedging off / budget spent: dispatch
            # inline on the runner thread, exactly the pre-hedging path
            # (no per-batch thread spawn for batches that could never
            # hedge anyway)
            t0 = time.monotonic()
            try:
                out = self._answer_once(wid, via, queries, diff,
                                        depoch=depoch, tid=tid)
            except Exception:
                self._record(via, False)
                raise
            self._record(via, True)
            dt = time.monotonic() - t0
            self.hedge.observe(wid, dt)
            obs_quantiles.observe("serve_dispatch_seconds", dt,
                                  trace_id=tid)
            return out
        results: _stdqueue.Queue = _stdqueue.Queue()

        def run(target: int, is_hedge: bool) -> None:
            t0 = time.monotonic()
            try:
                r = self._answer_once(wid, target, queries, diff,
                                      depoch=depoch, tid=tid)
            except Exception as e:  # noqa: BLE001 — collected below
                self._record(target, False)
                results.put((is_hedge, None, e, time.monotonic() - t0))
                return
            self._record(target, True)
            results.put((is_hedge, r, None, time.monotonic() - t0))

        threading.Thread(
            target=run, args=(via, False), daemon=True,
            name=f"dos-serve-primary-w{wid}").start()
        inflight = 1
        try:
            got = results.get(timeout=self.hedge.delay_s(wid))
            inflight -= 1
        except _stdqueue.Empty:
            got = None
            if self.hedge.try_issue():
                log.info("shard w%d batch slow on w%d; hedging to "
                         "replica w%d", wid, via, alt)
                threading.Thread(
                    target=run, args=(alt, True), daemon=True,
                    name=f"dos-serve-hedge-w{wid}").start()
                inflight += 1
        primary_errored = got is not None and got[1] is None
        while got is None or (got[1] is None and inflight > 0):
            # no answer yet, or the first completion was an error and
            # another attempt is still in flight: keep collecting
            nxt = results.get()
            inflight -= 1
            if nxt[1] is None and not nxt[0]:
                primary_errored = True
            got = nxt if got is None or got[1] is None else got
        is_hedge, out, exc, duration = got
        if out is None:
            raise exc
        if is_hedge and not primary_errored:
            # a WIN is the replica beating a live primary; a hedge that
            # survived because the primary ERRORED is failover, and
            # must not inflate the hedge-effectiveness headline
            M_WON.inc()
        self.hedge.observe(wid, duration)
        obs_quantiles.observe("serve_dispatch_seconds", duration,
                              trace_id=tid)
        return out
