"""Shard backends for the serving frontend.

A dispatcher answers one shard's batch:
``answer_batch(wid, queries [Q, 2], rconf, diff) -> (cost, plen,
finished)`` with each output aligned to ``queries``. Failures raise
:class:`DispatchError` (or anything else) — the frontend turns that
into per-request ``ERROR`` results and a circuit-breaker failure
record.

* :class:`EngineDispatcher` — in-process: one
  :class:`~..worker.engine.ShardEngine` per shard, built lazily on the
  shard's first batch (and optionally building missing CPD shard files
  on the spot, which is what lets ``dos-serve --test`` run from a bare
  checkout).
* :class:`FifoDispatcher` — the campaign wire against resident
  ``worker.server`` processes: per-batch query file into the shared
  dir, request through the command FIFO via
  ``transport.send_with_retry`` (capped-backoff retries, per-attempt
  answer FIFOs), and per-query answers read back from the
  ``<queryfile>.results`` sidecar (``RuntimeConfig.results`` wire
  extension).
* :class:`CallableDispatcher` — adapter for tests and the bench's
  resident-oracle serving mode.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading

import numpy as np

from ..parallel.partition import DistributionController
from ..transport import fifo as fifo_transport
from ..transport.fifo import answer_fifo_path, command_fifo_path
from ..transport.wire import (
    Request, RuntimeConfig, read_results_file, results_file_for,
    write_query_file,
)
from ..utils.config import ClusterConfig
from ..utils.log import get_logger

log = get_logger(__name__)


class DispatchError(RuntimeError):
    """A shard batch could not be answered."""


class EngineDispatcher:
    """In-process shard engines (the ``--backend inproc`` serving path
    and the smoke-test harness)."""

    def __init__(self, conf: ClusterConfig, graph=None,
                 dc: DistributionController | None = None,
                 alg: str = "table-search", build_missing: bool = False,
                 build_chunk: int = 512):
        from ..data.graph import Graph

        self.conf = conf
        self.graph = graph if graph is not None else Graph.from_xy(
            conf.xy_file)
        self.dc = dc if dc is not None else DistributionController(
            conf.partmethod, conf.partkey, conf.maxworker, self.graph.n)
        self.alg = alg
        self.build_missing = build_missing
        self.build_chunk = build_chunk
        self._engines: dict[int, object] = {}
        self._lock = threading.Lock()

    def _engine_for(self, wid: int):
        from ..worker.engine import ShardEngine

        with self._lock:
            eng = self._engines.get(wid)
            if eng is None:
                try:
                    eng = ShardEngine(self.graph, self.dc, wid,
                                      self.conf.outdir, alg=self.alg)
                except FileNotFoundError:
                    if not self.build_missing:
                        raise
                    from ..models.cpd import build_worker_shard

                    log.info("no CPD shard for worker %d in %s; building "
                             "in-process", wid, self.conf.outdir)
                    os.makedirs(self.conf.outdir, exist_ok=True)
                    build_worker_shard(self.graph, self.dc, wid,
                                       self.conf.outdir,
                                       chunk=self.build_chunk)
                    eng = ShardEngine(self.graph, self.dc, wid,
                                      self.conf.outdir, alg=self.alg)
                self._engines[wid] = eng
            return eng

    def answer_batch(self, wid: int, queries: np.ndarray,
                     rconf: RuntimeConfig, diff: str):
        cost, plen, fin, _stats = self._engine_for(wid).answer(
            queries, rconf, diff)
        return cost, plen, fin


class FifoDispatcher:
    """Wire dispatch to resident workers. Every batch gets UNIQUE
    ``query.serve.*`` / answer-FIFO names (pid + per-shard sequence):
    a timed-out batch's request stays queued in the worker's command
    FIFO with no way to cancel it, and its late ``.results`` write must
    land in that batch's own file — never be mistaken for (or tear the
    bytes of) a newer batch's sidecar. The previous batch's files are
    swept on the shard's next dispatch (one batch in flight per shard,
    so by then the old reply either landed or lost). Serving answer
    FIFOs stay disjoint from campaign ones (``answer.<host><wid>``) so
    a campaign sharing the nfs dir cannot cross replies with the
    frontend."""

    def __init__(self, conf: ClusterConfig,
                 timeout: float | None = None,
                 policy: fifo_transport.RetryPolicy | None = None):
        self.conf = conf
        self.timeout = (timeout if timeout is not None
                        else fifo_transport.DEFAULT_TIMEOUT)
        self.policy = policy
        self._seq = itertools.count()
        self._prev_qfile: dict[int, str] = {}

    def _sweep_prev(self, wid: int) -> None:
        prev = self._prev_qfile.pop(wid, None)
        if not prev:
            return
        for p in (prev, results_file_for(prev)):
            try:
                os.remove(p)
            except OSError:
                pass

    def close(self) -> None:
        """Sweep every shard's last batch files (called by
        ``ServingFrontend.stop`` — without it each shard's FINAL
        ``query.serve.*``/``.results`` pair would outlive the service
        on the shared nfs dir)."""
        for wid in list(self._prev_qfile):
            self._sweep_prev(wid)

    def answer_batch(self, wid: int, queries: np.ndarray,
                     rconf: RuntimeConfig, diff: str):
        host = self.conf.workers[wid]
        nfs = self.conf.nfs
        self._sweep_prev(wid)
        tag = f"{os.getpid()}.{next(self._seq)}"
        qfile = os.path.join(nfs, f"query.serve.{host}{wid}.{tag}")
        self._prev_qfile[wid] = qfile
        write_query_file(qfile, queries)
        req = Request(
            dataclasses.replace(rconf, results=True), qfile,
            answer_fifo_path(nfs, host, wid) + f".serve.{tag}", diff)
        row = fifo_transport.send_with_retry(
            host, req, command_fifo_path(wid), timeout=self.timeout,
            policy=self.policy, wid=wid)
        if not row.ok:
            raise DispatchError(
                f"worker {wid} on {host} failed a serving batch "
                f"({len(queries)} queries)")
        try:
            cost, plen, fin = read_results_file(results_file_for(qfile))
        except (OSError, ValueError) as e:
            # an old server (pre-`results` wire key) answers the stats
            # line but writes no sidecar — a hard error here, not a
            # silent all-zeros answer
            raise DispatchError(
                f"worker {wid} on {host} returned no results sidecar "
                f"(server predates the wire extension?): {e}") from e
        if len(cost) != len(queries):
            raise DispatchError(
                f"worker {wid} results length {len(cost)} != batch "
                f"{len(queries)}")
        return cost, plen, fin


class CallableDispatcher:
    """Wrap ``fn(wid, queries, rconf, diff) -> (cost, plen, finished)``."""

    def __init__(self, fn):
        self.fn = fn

    def answer_batch(self, wid: int, queries: np.ndarray,
                     rconf: RuntimeConfig, diff: str):
        return self.fn(wid, queries, rconf, diff)
