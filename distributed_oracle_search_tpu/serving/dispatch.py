"""Shard backends for the serving frontend.

A dispatcher answers one shard's batch:
``answer_batch(wid, queries [Q, 2], rconf, diff) -> (cost, plen,
finished)`` with each output aligned to ``queries``. Failures raise
:class:`DispatchError` (or anything else) — the frontend turns that
into per-request ``ERROR`` results and a circuit-breaker failure
record.

* :class:`EngineDispatcher` — in-process: one
  :class:`~..worker.engine.ShardEngine` per shard, built lazily on the
  shard's first batch (and optionally building missing CPD shard files
  on the spot, which is what lets ``dos-serve --test`` run from a bare
  checkout).
* :class:`FifoDispatcher` — the campaign wire against resident
  ``worker.server`` processes: per-batch query file into the shared
  dir, request through the command FIFO via
  ``transport.send_with_retry`` (capped-backoff retries, per-attempt
  answer FIFOs), and per-query answers read back from the
  ``<queryfile>.results`` sidecar (``RuntimeConfig.results`` wire
  extension).
* :class:`RpcDispatcher` — the streaming data plane
  (``DOS_TRANSPORT=rpc``): one persistent multiplexed socket per
  worker (``transport.rpc``), queries and per-query answers riding as
  raw ndarray frame segments — no files, no FIFO rendezvous, no
  text parse on the hot path.
* :class:`AutoDispatcher` — ``DOS_TRANSPORT=auto``: RPC first, with a
  sticky per-lane fallback to the FIFO wire when a worker has no RPC
  listener (mixed fleets mid-rollout).
* :class:`CallableDispatcher` — adapter for tests and the bench's
  resident-oracle serving mode.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import time
import zlib

import numpy as np

from ..integrity.fingerprint import (
    FingerprintError, M_FP_MISMATCH, answer_fingerprint,
)
from ..obs import metrics as obs_metrics
from ..parallel.partition import DistributionController
from ..testing import faults
from ..transport import fifo as fifo_transport
from ..transport import rpc as rpc_transport
from ..transport.fifo import answer_fifo_path, command_fifo_path
from ..transport.frames import TransportError
from ..transport.wire import (
    Request, RuntimeConfig, paths_file_for, read_paths_file,
    read_results_file, results_file_for, write_query_file,
)
from ..utils.config import ClusterConfig
from ..utils.locks import OrderedLock
from ..utils.log import get_logger

log = get_logger(__name__)

M_HEDGE_QFILE_REUSED = obs_metrics.counter(
    "serve_hedge_qfile_reused_total",
    "hedged FIFO dispatches that reused the primary attempt's already-"
    "written query file instead of paying a second filesystem write")
H_RPC_DISPATCH = obs_metrics.histogram(
    "rpc_dispatch_seconds",
    "one serving batch over the socket transport, send to decoded "
    "reply (the RPC twin of the FIFO lane inside "
    "serve_dispatch_seconds)")


class DispatchError(RuntimeError):
    """A shard batch could not be answered."""


class RpcUnavailableError(DispatchError):
    """The worker has no reachable RPC listener (connect refused /
    socket absent) — the ``auto`` transport's FIFO-fallback signal, as
    opposed to a worker that answered and failed."""


def _fp_guard(wid: int, cost, plen, fin, rconf):
    """In-process twin of the wire fingerprint check: fingerprint the
    answers the engine just returned, run them past the
    ``corrupt-answer`` fault point (the only way bytes can rot between
    an in-process engine and its caller is injection), and re-verify.
    A mismatch raises :class:`DispatchError` so the frontend's failover
    machinery retries — a corrupted answer is never handed up. No-op
    unless ``rconf.answer_fp``."""
    if not getattr(rconf, "answer_fp", False):
        return cost, plen, fin
    fp = answer_fingerprint(cost, plen, fin)
    if faults.inject("corrupt-answer", wid) is not None:
        cost = np.array(cost, np.int64, copy=True)
        if len(cost):
            cost[0] ^= 1
    if answer_fingerprint(cost, plen, fin) != fp:
        M_FP_MISMATCH.inc()
        raise DispatchError(
            f"shard {wid}: answer fingerprint mismatch on the "
            "in-process lane — corrupted answer suppressed")
    return cost, plen, fin


class EngineDispatcher:
    """In-process shard engines (the ``--backend inproc`` serving path
    and the smoke-test harness).

    ``answer_batch``'s ``via`` routes the batch through a REPLICA
    host's engine (failover off an open breaker, or the hedge's
    duplicate): engines are keyed ``(shard, via)`` so the primary's and
    each replica's row sets load independently — with ``build_missing``
    (the ``--test`` path) a missing replica block set is materialized
    lazily on first use (copied from the primary when it exists,
    recomputed otherwise), so R=2 serve tests need no pre-build step."""

    def __init__(self, conf: ClusterConfig, graph=None,
                 dc: DistributionController | None = None,
                 alg: str = "table-search", build_missing: bool = False,
                 build_chunk: int = 512):
        from ..data.graph import Graph

        self.conf = conf
        self.graph = graph if graph is not None else Graph.from_xy(
            conf.xy_file)
        self.dc = dc if dc is not None else DistributionController(
            conf.partmethod, conf.partkey, conf.maxworker, self.graph.n,
            replication=conf.effective_replication())
        self.alg = alg
        self.build_missing = build_missing
        self.build_chunk = build_chunk
        self._engines: dict[tuple, object] = {}
        #: per-(shard, via) lane serialization: an ABANDONED hedge
        #: loser's thread can still be inside ``eng.answer`` when the
        #: batcher dispatches the next batch to the same lane — without
        #: the lane lock the loser's late return overwrites
        #: ``last_paths`` under the next batch's read and scoped
        #: invalidation re-keys entries with another batch's signatures
        self._lane_locks: dict[tuple, OrderedLock] = {}
        self._lock = OrderedLock("serving.EngineDispatcher")

    def _build_missing_shard(self, shard: int, replica: int) -> None:
        from ..models.cpd import (
            build_worker_shard, copy_replica_blocks,
        )

        log.info("no CPD %s for shard %d in %s; building in-process",
                 f"replica r{replica}" if replica else "shard", shard,
                 self.conf.outdir)
        os.makedirs(self.conf.outdir, exist_ok=True)
        if replica:
            copy_replica_blocks(self.dc, shard, replica,
                                self.conf.outdir)
        build_worker_shard(self.graph, self.dc, shard, self.conf.outdir,
                           chunk=self.build_chunk, replica=replica)

    def _rank_for(self, wid: int, via: int) -> int:
        """Which block set lane ``(wid, via)`` serves from: the via
        worker's rank in the shard's replica chain — or the PRIMARY set
        when ``via`` is outside the chain (a membership-migration
        adopter answering dual-read traffic before its epoch commits,
        or after a commit reassigned ownership off-chain)."""
        if via == wid:
            return 0
        try:
            return self.dc.replica_rank(wid, via)
        except ValueError:
            return 0

    def _engine_for(self, wid: int, via: int | None = None):
        from ..worker.engine import ShardEngine

        via = wid if via is None else int(via)
        rank = self._rank_for(wid, via)
        with self._lock:
            eng = self._engines.get((wid, via))
            if eng is None:
                try:
                    eng = ShardEngine(self.graph, self.dc, via,
                                      self.conf.outdir, alg=self.alg,
                                      shard=wid, replica=rank)
                except (FileNotFoundError, ValueError):
                    # ValueError covers a PARTIAL block set (a killed
                    # lazy build left some blocks; the row count fails
                    # the partition check): the resumed build below
                    # recomputes exactly the missing tail. A genuine
                    # partition mismatch rebuilds to the same mismatch
                    # and the retry's raise propagates it.
                    if not self.build_missing:
                        raise
                    self._build_missing_shard(wid, rank)
                    eng = ShardEngine(self.graph, self.dc, via,
                                      self.conf.outdir, alg=self.alg,
                                      shard=wid, replica=rank)
                self._engines[(wid, via)] = eng
            return eng

    def _lane(self, wid: int, via: int | None):
        """The lane's engine plus its serialization lock."""
        via = wid if via is None else int(via)
        eng = self._engine_for(wid, via)
        with self._lock:
            lock = self._lane_locks.setdefault(
                (wid, via), OrderedLock("serving.EngineDispatcher.lane"))
        return eng, lock

    def answer_batch(self, wid: int, queries: np.ndarray,
                     rconf: RuntimeConfig, diff: str,
                     via: int | None = None):
        eng, lane = self._lane(wid, via)
        with lane:
            cost, plen, fin, _stats = eng.answer(queries, rconf, diff)
        return _fp_guard(wid, cost, plen, fin, rconf)

    def answer_batch_paths(self, wid: int, queries: np.ndarray,
                           rconf: RuntimeConfig, diff: str,
                           via: int | None = None):
        """``answer_batch`` plus the batch's path prefixes — the
        live-traffic frontend sets ``rconf.sig_k`` and keys scoped cache
        invalidation off them. Returns ``(cost, plen, fin, nodes,
        moves)``; the path halves are ``None`` when the engine captured
        none. The lane lock covers the answer AND the ``last_paths``
        read: the frontend keeps one batch in flight per lane, but an
        ABANDONED hedge loser is still running on its lane when the
        winner returns — without the lock its late return could
        overwrite ``last_paths`` under this batch's read."""
        eng, lane = self._lane(wid, via)
        with lane:
            cost, plen, fin, _stats = eng.answer(queries, rconf, diff)
            nodes, moves = eng.last_paths or (None, None)
        cost, plen, fin = _fp_guard(wid, cost, plen, fin, rconf)
        return cost, plen, fin, nodes, moves


class FifoDispatcher:
    """Wire dispatch to resident workers. Every batch gets UNIQUE
    ``query.serve.*`` / answer-FIFO names (pid + per-shard sequence):
    a timed-out batch's request stays queued in the worker's command
    FIFO with no way to cancel it, and its late ``.results`` write must
    land in that batch's own file — never be mistaken for (or tear the
    bytes of) a newer batch's sidecar. The previous batch's files are
    swept on the shard's next dispatch (one batch in flight per shard,
    so by then the old reply either landed or lost). Serving answer
    FIFOs stay disjoint from campaign ones (``answer.<host><wid>``) so
    a campaign sharing the nfs dir cannot cross replies with the
    frontend."""

    def __init__(self, conf: ClusterConfig,
                 timeout: float | None = None,
                 policy: fifo_transport.RetryPolicy | None = None,
                 host_of=None):
        self.conf = conf
        self.timeout = (timeout if timeout is not None
                        else fifo_transport.DEFAULT_TIMEOUT)
        self.policy = policy
        #: worker id -> ssh host. The default reads the conf's static
        #: roster (wrapping for ids past it — an elastic JOIN mints
        #: worker ids the conf never listed); a membership-aware caller
        #: passes the live roster resolver
        #: (``MembershipController.host_of``) instead.
        self.host_of = host_of or (
            lambda via: self.conf.workers[via % len(self.conf.workers)])
        self._seq = itertools.count()
        #: per dispatch lane ((shard, via) pair): the previous batch's
        #: query file and answer-FIFO base, swept on the lane's next
        #: dispatch / at close
        self._prev: dict[tuple, tuple[str, str]] = {}
        #: one mutex per lane: hedged dispatch broke the frontend's
        #: one-batch-per-shard invariant for THIS layer (a losing
        #: primary attempt can still be in flight when the runner
        #: thread dispatches the shard's next batch on the same lane),
        #: and the next batch's _sweep_prev must not unlink the loser's
        #: in-flight query file / answer FIFOs. The worker's command
        #: FIFO serializes same-worker batches anyway, so the lock adds
        #: ordering, not latency.
        self._lane_locks: dict[tuple, OrderedLock] = {}
        self._locks_guard = OrderedLock("serving.FifoDispatcher.guard")
        #: live shared query files keyed by batch content digest: a
        #: HEDGE duplicate dispatches the same (shard, queries, diff)
        #: while the primary attempt is still in flight — it reuses the
        #: primary's already-written query file instead of paying a
        #: second filesystem round-trip per candidate (ROADMAP item 3
        #: callout). Entry = ``[qfile, refs, orphaned, qbytes]``:
        #: refcounted so a LATER identical batch (skewed repeats)
        #: writes fresh (reuse is scoped to overlapping duplicates);
        #: ``qbytes`` is compared on every hit so a crc32 collision
        #: can never alias two different batches onto one file; and
        #: ``orphaned`` marks a file whose writer lane moved on while
        #: a reuser was still in flight — the LAST reference unlinks
        #: it instead of the writer's sweep.
        self._shared_q: dict[tuple, list] = {}

    def _lane_lock(self, lane: tuple) -> OrderedLock:
        with self._locks_guard:
            lock = self._lane_locks.get(lane)
            if lock is None:
                lock = self._lane_locks[lane] = OrderedLock(
                    "serving.FifoDispatcher.lane")
            return lock

    def _sweep_prev(self, lane: tuple) -> None:
        prev = self._prev.pop(lane, None)
        if not prev:
            return
        import glob as _glob
        import stat as _stat

        qfile, answer_base = prev
        if qfile:       # a hedge lane that REUSED the primary's query
            # file books (None, fifos): only the writer lane sweeps it
            with self._locks_guard:
                live = next((e for e in self._shared_q.values()
                             if e[0] == qfile and e[1] > 0), None)
                if live is not None:
                    # a hedge duplicate on ANOTHER lane still has this
                    # file in flight: defer the unlink to the last
                    # reference's release instead of tearing the
                    # in-flight attempt's read
                    live[2] = True
                    qfile = None
        if qfile:
            self._unlink_batch_files(qfile)
        # the per-attempt answer FIFOs (<base>.a<n>) are normally
        # removed by the transfer script's own `rm -f`; a script killed
        # on timeout never gets there, and an orphaned FIFO on the
        # shared dir outlives the service. Only FIFOs are touched.
        for p in _glob.glob(answer_base + ".a*"):
            try:
                if _stat.S_ISFIFO(os.stat(p).st_mode):
                    os.remove(p)
            except OSError:
                pass

    @staticmethod
    def _unlink_batch_files(qfile: str) -> None:
        for p in (qfile, results_file_for(qfile), paths_file_for(qfile)):
            try:
                os.remove(p)
            except OSError:
                pass

    def close(self) -> None:
        """Sweep every lane's last batch files — query file,
        ``.results`` sidecar AND any per-attempt ``answer.*`` FIFOs a
        timed-out transfer script orphaned (called by
        ``ServingFrontend.stop``; without it the FINAL batch's debris
        would outlive the service on the shared nfs dir). Lane locks
        are taken best-effort: a loser attempt still in flight at
        shutdown must not stall the stop for its full wire timeout."""
        for lane in list(self._prev):
            lock = self._lane_lock(lane)
            got = lock.acquire(timeout=2.0)
            try:
                self._sweep_prev(lane)
            finally:
                if got:
                    lock.release()

    def answer_batch(self, wid: int, queries: np.ndarray,
                     rconf: RuntimeConfig, diff: str,
                     via: int | None = None):
        return self._dispatch(wid, queries, rconf, diff, via,
                              want_paths=False)

    def answer_batch_paths(self, wid: int, queries: np.ndarray,
                           rconf: RuntimeConfig, diff: str,
                           via: int | None = None):
        """Wire twin of :meth:`EngineDispatcher.answer_batch_paths`:
        when ``rconf.sig_k`` (or ``extract``) made the server write a
        ``.paths`` sidecar, read it back next to the ``.results`` one.
        An old server that filtered the unknown key ships no sidecar —
        the path halves come back ``None`` and the cache degrades to
        conservative invalidation, never an error."""
        return self._dispatch(wid, queries, rconf, diff, via,
                              want_paths=True)

    def _dispatch(self, wid: int, queries: np.ndarray,
                  rconf: RuntimeConfig, diff: str,
                  via: int | None, want_paths: bool):
        via = wid if via is None else int(via)
        host = self.host_of(via)
        nfs = self.conf.nfs
        lane = (wid, via)
        qbytes = np.ascontiguousarray(queries, np.int64).tobytes()
        qkey = (wid, len(queries), zlib.crc32(qbytes), diff)
        with self._lane_lock(lane):
            self._sweep_prev(lane)
            tag = f"{os.getpid()}.{next(self._seq)}"
            answer_base = (answer_fifo_path(nfs, host, via)
                           + f".serve.{tag}")
            with self._locks_guard:
                shared = self._shared_q.get(qkey)
                # content check, not just the crc key: a 32-bit
                # collision must degrade to a fresh write, never alias
                # another batch's queries onto this dispatch
                if shared is not None and shared[3] == qbytes:
                    shared[1] += 1
                else:
                    shared = None
            if shared is not None:
                # a concurrent duplicate of this exact batch (the hedge
                # lane) — the primary's query file is still live on the
                # shared dir; reuse it and sweep only our own FIFOs
                qfile = shared[0]
                self._prev[lane] = (None, answer_base)
                M_HEDGE_QFILE_REUSED.inc()
            else:
                qfile = os.path.join(nfs,
                                     f"query.serve.{host}{via}.{tag}")
                self._prev[lane] = (qfile, answer_base)
                write_query_file(qfile, queries)
                with self._locks_guard:
                    self._shared_q[qkey] = [qfile, 1, False, qbytes]
            req = Request(
                dataclasses.replace(rconf, results=True), qfile,
                answer_base, diff)
            try:
                # dos-lint: disable=lock-scope -- holding the lane lock
                #   across the wire send is the invariant, not an
                #   accident: the lock exists to serialize same-lane
                #   batches so the next batch's _sweep_prev can't
                #   unlink THIS batch's in-flight files; the worker's
                #   command FIFO serializes same-worker sends anyway,
                #   so it adds ordering, not wait
                row = fifo_transport.send_with_retry(
                    host, req, command_fifo_path(via),
                    timeout=self.timeout, policy=self.policy, wid=via)
                if not row.ok:
                    detail = (
                        " (STALE_DIFF: worker behind the diff stream)"
                        if row.stale_diff else
                        " (STALE_EPOCH: worker behind the partition "
                        "table)" if row.stale_epoch else "")
                    raise DispatchError(
                        f"worker {via} on {host} failed a serving "
                        f"batch ({len(queries)} queries for shard "
                        f"{wid})" + detail)
                try:
                    cost, plen, fin = read_results_file(
                        results_file_for(qfile))
                except FingerprintError as e:
                    # the sidecar EXISTS but its answer bytes failed
                    # the crc32 check — a data fault, not a version
                    # skew; fail over without the legacy-server hint
                    raise DispatchError(
                        f"worker {via} on {host} returned a corrupted "
                        f"results sidecar: {e}") from e
                except (OSError, ValueError) as e:
                    # an old server (pre-`results` wire key) answers
                    # the stats line but writes no sidecar — a hard
                    # error here, not a silent all-zeros answer
                    raise DispatchError(
                        f"worker {via} on {host} returned no results "
                        f"sidecar (server predates the wire "
                        f"extension?): {e}") from e
                if len(cost) != len(queries):
                    raise DispatchError(
                        f"worker {via} results length {len(cost)} != "
                        f"batch {len(queries)}")
                if not want_paths:
                    return cost, plen, fin
                nodes = moves = None
                try:
                    nodes, moves = read_paths_file(
                        paths_file_for(qfile))
                except (OSError, ValueError):
                    pass   # old server / no extraction: signature-less
                return cost, plen, fin, nodes, moves
            finally:
                # this attempt no longer pins the shared query file; a
                # LATER identical batch must write its own. The file
                # itself is swept by the writer lane's next dispatch —
                # unless that sweep already came and went while a
                # reuser was in flight (orphaned): then the LAST
                # reference unlinks it here
                cleanup = None
                with self._locks_guard:
                    ent = self._shared_q.get(qkey)
                    if ent is not None and ent[0] == qfile:
                        ent[1] -= 1
                        if ent[1] <= 0:
                            self._shared_q.pop(qkey, None)
                            if ent[2]:
                                cleanup = ent[0]
                if cleanup:
                    self._unlink_batch_files(cleanup)


class RpcDispatcher:
    """The streaming data plane: one persistent, multiplexed socket per
    worker (``transport.rpc``), frames instead of files.

    Queries ship as a raw int64 payload segment, per-query answers come
    back as cost/plen/fin segments in the correlated reply frame, and
    path prefixes (``rconf.sig_k``) ride two more segments — the FIFO
    lane's query file, ``.results`` sidecar, ``.paths`` sidecar, and
    both blocking FIFO rendezvous all disappear from the hot path.
    Transport failures (dead socket, torn frame, timeout) and explicit
    ``busy`` backpressure frames raise :class:`DispatchError` flavors
    the frontend already treats as breaker failures + failover; a
    worker with no listener at all raises
    :class:`RpcUnavailableError` (the ``auto`` fallback signal)."""

    def __init__(self, conf: ClusterConfig,
                 timeout: float | None = None, host_of=None):
        self.conf = conf
        #: None = defer to DOS_RPC_TIMEOUT_S (resolved inside RpcClient)
        self.timeout = timeout
        self.host_of = host_of or (
            lambda via: self.conf.workers[via % len(self.conf.workers)])
        self._clients: dict[int, rpc_transport.RpcClient] = {}
        self._guard = OrderedLock("serving.RpcDispatcher")

    def _client(self, via: int) -> rpc_transport.RpcClient:
        # the endpoint is re-resolved on EVERY dispatch (the
        # FifoDispatcher host_of contract): a live-membership host
        # change retires the stale client and dials the worker's new
        # home instead of flapping on the dead one forever
        ep = rpc_transport.endpoint_for(via, host=self.host_of(via))
        stale = None
        with self._guard:
            c = self._clients.get(via)
            if c is not None and c.endpoint != ep:
                stale, c = c, None
            if c is None:
                c = self._clients[via] = rpc_transport.RpcClient(
                    ep, timeout_s=self.timeout)
        if stale is not None:
            log.info("worker %d rpc endpoint moved %s -> %s; "
                     "reconnecting", via,
                     rpc_transport.endpoint_str(stale.endpoint),
                     rpc_transport.endpoint_str(ep))
            stale.close(join_s=1.0)
        return c

    def answer_batch(self, wid: int, queries: np.ndarray,
                     rconf: RuntimeConfig, diff: str,
                     via: int | None = None):
        return self._dispatch(wid, queries, rconf, diff, via,
                              want_paths=False)

    def answer_batch_paths(self, wid: int, queries: np.ndarray,
                           rconf: RuntimeConfig, diff: str,
                           via: int | None = None):
        return self._dispatch(wid, queries, rconf, diff, via,
                              want_paths=True)

    def _dispatch(self, wid: int, queries: np.ndarray,
                  rconf: RuntimeConfig, diff: str,
                  via: int | None, want_paths: bool):
        via = wid if via is None else int(via)
        client = self._client(via)
        rc = dataclasses.replace(rconf, results=True)
        q = np.ascontiguousarray(
            np.asarray(queries, np.int64).reshape(-1, 2))
        t0 = time.monotonic()
        try:
            fr = client.call(
                rpc_transport.request_header(rc, diff, wid=via), [q])
        except rpc_transport.RpcUnavailable as e:
            raise RpcUnavailableError(
                f"worker {via} has no rpc listener: {e}") from e
        except rpc_transport.RpcBusy as e:
            raise DispatchError(
                f"worker {via} answered BUSY (rpc credit window): {e}"
            ) from e
        except TransportError as e:
            raise DispatchError(
                f"worker {via} rpc transport failed (retryable): {e}"
            ) from e
        H_RPC_DISPATCH.observe(time.monotonic() - t0)
        row = rpc_transport.decode_reply_row(fr)
        if not row.ok:
            detail = (" (STALE_DIFF: worker behind the diff stream)"
                      if row.stale_diff else
                      " (STALE_EPOCH: worker behind the partition "
                      "table)" if row.stale_epoch else "")
            raise DispatchError(
                f"worker {via} failed a serving batch over rpc "
                f"({len(queries)} queries for shard {wid})" + detail)
        if not fr.header.get("res") or len(fr.arrays) < 3:
            raise DispatchError(
                f"worker {via} rpc reply carried no result segments "
                f"(server predates the wire extension?)")
        cost = np.asarray(fr.arrays[0], np.int64)
        plen = np.asarray(fr.arrays[1], np.int64)
        fin = np.asarray(fr.arrays[2]) != 0
        if len(cost) != len(queries):
            raise DispatchError(
                f"worker {via} rpc results length {len(cost)} != "
                f"batch {len(queries)}")
        fp_want = fr.header.get("fp")
        if fp_want is not None:
            # RuntimeConfig.answer_fp wire extension: the server
            # fingerprinted the answer segments at birth; re-check
            # after the socket hop before trusting them
            got = answer_fingerprint(cost, plen, fin)
            if got != int(fp_want):
                M_FP_MISMATCH.inc()
                raise DispatchError(
                    f"worker {via} rpc reply failed the answer "
                    f"fingerprint check (header {int(fp_want):08x}, "
                    f"computed {got:08x}) — corrupted answer "
                    "suppressed")
        if not want_paths:
            return cost, plen, fin
        nodes = moves = None
        if fr.header.get("paths") and len(fr.arrays) >= 5:
            nodes = np.asarray(fr.arrays[3], np.int64)
            moves = np.asarray(fr.arrays[4], np.int64)
        return cost, plen, fin, nodes, moves

    def probe(self, via: int):
        """Breaker-healing hook: the ping/HealthStatus vocabulary over
        a fresh connection (None on failure, like the FIFO probe)."""
        return rpc_transport.probe(via, host=self.host_of(via))

    def statusz(self) -> dict:
        """The ``/statusz`` transport connection table."""
        with self._guard:
            return {
                "mode": "rpc",
                "connections": {str(via): c.statusz()
                                for via, c in self._clients.items()},
            }

    def close(self) -> None:
        with self._guard:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()


class AutoDispatcher:
    """``DOS_TRANSPORT=auto``: the streaming lane with a sticky
    per-worker FIFO fallback.

    Each lane tries RPC first; a worker with NO listener (connect
    refused — the pre-RPC half of a mixed fleet mid-rollout) drops that
    lane to the FIFO wire and stays there. A worker that ANSWERED on
    RPC and then failed is a worker failure, not a transport gap — it
    surfaces as the normal retryable DispatchError and walks the
    breaker/failover path without switching transports under a chaos
    drill."""

    def __init__(self, conf: ClusterConfig,
                 timeout: float | None = None, policy=None,
                 host_of=None):
        self.rpc = RpcDispatcher(conf, timeout=timeout,
                                 host_of=host_of)
        self.fifo = FifoDispatcher(conf, timeout=timeout, policy=policy,
                                   host_of=host_of)
        self._fifo_only: set[int] = set()
        self._guard = OrderedLock("serving.AutoDispatcher")

    @property
    def host_of(self):
        return self.rpc.host_of

    @host_of.setter
    def host_of(self, fn) -> None:
        self.rpc.host_of = fn
        self.fifo.host_of = fn

    def _route(self, meth: str, wid: int, queries, rconf, diff, via):
        key = wid if via is None else int(via)
        with self._guard:
            use_fifo = key in self._fifo_only
        if not use_fifo:
            try:
                return getattr(self.rpc, meth)(wid, queries, rconf,
                                               diff, via=via)
            except RpcUnavailableError as e:
                with self._guard:
                    self._fifo_only.add(key)
                log.warning("worker %d has no rpc listener (%s); lane "
                            "falls back to the FIFO wire", key, e)
        return getattr(self.fifo, meth)(wid, queries, rconf, diff,
                                        via=via)

    def answer_batch(self, wid: int, queries: np.ndarray,
                     rconf: RuntimeConfig, diff: str,
                     via: int | None = None):
        return self._route("answer_batch", wid, queries, rconf, diff,
                           via)

    def answer_batch_paths(self, wid: int, queries: np.ndarray,
                           rconf: RuntimeConfig, diff: str,
                           via: int | None = None):
        return self._route("answer_batch_paths", wid, queries, rconf,
                           diff, via)

    def statusz(self) -> dict:
        out = self.rpc.statusz()
        out["mode"] = "auto"
        with self._guard:
            out["fifo_fallback_lanes"] = sorted(self._fifo_only)
        return out

    def close(self) -> None:
        self.rpc.close()
        self.fifo.close()


class CallableDispatcher:
    """Wrap ``fn(wid, queries, rconf, diff) -> (cost, plen, finished)``.

    ``via`` is accepted for interface parity and ignored: a callable
    backend has no per-worker placement, so replica routing is a no-op
    (tests that need via-sensitive behavior implement ``answer_batch``
    directly)."""

    def __init__(self, fn):
        self.fn = fn

    def answer_batch(self, wid: int, queries: np.ndarray,
                     rconf: RuntimeConfig, diff: str,
                     via: int | None = None):
        return self.fn(wid, queries, rconf, diff)
