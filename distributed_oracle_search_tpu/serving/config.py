"""Serving knobs (``DOS_SERVE_*`` env family).

One frozen dataclass holds every tunable of the online path so the
frontend, queues, batchers, and cache agree on a single source of truth,
and ``from_env`` follows the repo-wide env policy (``utils.env``): a
malformed value degrades to the default with a log line, never a crash.
"""

from __future__ import annotations

import dataclasses

from ..utils.env import env_cast
from ..utils.log import get_logger

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Online-serving tunables.

    * ``queue_depth`` — bound of each shard's request queue; a full
      queue sheds ``BUSY`` immediately (admission control, never a
      silent hang). Env: ``DOS_SERVE_QUEUE_DEPTH``.
    * ``max_batch`` — flush threshold of the micro-batcher. MUST be a
      power of two: batches pad to the next power of two inside
      ``ShardEngine.answer``, so a pow2 cap means steady-state traffic
      reuses the handful of compiled programs the engine keys on
      ``qpad`` instead of compiling per batch size. Env:
      ``DOS_SERVE_MAX_BATCH``.
    * ``max_wait_ms`` — how long the micro-batcher lets the FIRST
      request of a forming batch wait before flushing a partial batch:
      the few milliseconds of waiting traded for fuller compiled-program
      batches. Env: ``DOS_SERVE_MAX_WAIT_MS``.
    * ``cache_bytes`` — budget of the LRU result cache; ``0`` disables
      caching. Env: ``DOS_SERVE_CACHE_BYTES``.
    * ``deadline_ms`` — per-request deadline from submit; a request
      still queued past it completes ``TIMEOUT`` instead of occupying
      a batch slot. Env: ``DOS_SERVE_DEADLINE_MS``.
    """

    queue_depth: int = 256
    max_batch: int = 64
    max_wait_ms: float = 5.0
    cache_bytes: int = 16 << 20
    deadline_ms: float = 10_000.0

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        """Env-derived config; keyword overrides (CLI flags) win when
        not ``None``. Env policy (``utils.env``): a well-typed but
        INVALID env value (e.g. ``DOS_SERVE_MAX_BATCH=48``, not a power
        of two) degrades to the default with a log line like an
        unparseable one — only explicit overrides raise."""
        vals = dict(
            queue_depth=env_cast("DOS_SERVE_QUEUE_DEPTH",
                                 cls.queue_depth, int),
            max_batch=env_cast("DOS_SERVE_MAX_BATCH", cls.max_batch, int),
            max_wait_ms=env_cast("DOS_SERVE_MAX_WAIT_MS",
                                 cls.max_wait_ms, float),
            cache_bytes=env_cast("DOS_SERVE_CACHE_BYTES",
                                 cls.cache_bytes, int),
            deadline_ms=env_cast("DOS_SERVE_DEADLINE_MS",
                                 cls.deadline_ms, float),
        )
        for field, value in list(vals.items()):
            try:
                cls(**{field: value}).validate()
            except ValueError as e:
                log.warning("ignoring invalid DOS_SERVE_%s=%r (%s); "
                            "using %r", field.upper(), value, e,
                            getattr(cls, field))
                vals[field] = getattr(cls, field)
        vals.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**vals).validate()

    def validate(self) -> "ServeConfig":
        if self.queue_depth <= 0:
            raise ValueError("queue_depth must be positive")
        if self.max_batch <= 0 or self.max_batch & (self.max_batch - 1):
            raise ValueError(
                f"max_batch must be a positive power of two (got "
                f"{self.max_batch}): batches pad to pow2 in the engine, "
                "and a pow2 cap keeps the compiled-program set small")
        if self.max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if self.cache_bytes < 0:
            raise ValueError("cache_bytes must be >= 0")
        if self.deadline_ms <= 0:
            raise ValueError("deadline_ms must be positive")
        return self

    @property
    def max_wait_s(self) -> float:
        return self.max_wait_ms / 1e3

    @property
    def deadline_s(self) -> float:
        return self.deadline_ms / 1e3
