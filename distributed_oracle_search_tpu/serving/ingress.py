"""Line-protocol ingress: stdin, unix socket, and file-tail.

Protocol (one request per line, responses in request order):

    request:   ``<s> <t>``            (node ids; blank lines and
                                       ``#`` comments are skipped)
               ``mat <s> <t1> ... <tk>``   one-to-many ETA matrix row
               ``alt <s> <t> <k>``         k-alternative routes
               ``rev <s> <t>``             reverse (return-trip) route
    response:  ``OK <s> <t> <cost> <plen> <finished> [cached]``
               ``MAT <s> <k> <c1> ... <ck>``    (-1 = unanswered)
               ``ALT <s> <t> <n> <c1> ... <cn>`` (ascending, n <= k)
               ``REV <s> <t> <cost> <plen> <finished>``
               ``BUSY|UNAVAILABLE|TIMEOUT|ERROR <s> <t> [detail]``
    control:   ``quit``               closes the session

The typed family sentences (``traffic.families``) are accepted only
when the caller wires a :class:`~..traffic.QueryFamilies` planner;
without one they answer ``ERROR`` like any malformed line, so a plain
pair-only deployment's protocol surface is unchanged.

The reader NEVER blocks per request — it submits and moves on, which is
what lets back-to-back lines coalesce into real micro-batches; a writer
thread completes responses in submission order. A malformed line gets an
in-order ``ERROR -1 -1 malformed-line`` response instead of desyncing
the stream.
"""

from __future__ import annotations

import os
import queue as _stdqueue
import socket
import threading
import time

from ..utils.log import get_logger
from .frontend import ServingFrontend
from .request import ERROR, Future, ServeResult

log = get_logger(__name__)

QUIT_TOKEN = "quit"


def parse_query_line(line: str) -> tuple[int, int]:
    toks = line.split()
    if len(toks) != 2:
        raise ValueError(f"want '<s> <t>', got {line!r}")
    return int(toks[0]), int(toks[1])


def serve_stream(frontend: ServingFrontend, rfile, wfile,
                 result_timeout_s: float | None = None,
                 families=None) -> int:
    """Run the line protocol over a text-file pair until EOF or
    ``quit``; returns the number of requests handled. The writer drains
    futures in submission order on its own thread so slow shards never
    stall ingestion (ingestion is bounded by the shard queues, which is
    the point). ``families`` (a ``traffic.QueryFamilies``) enables the
    typed mat/alt/rev sentences."""
    if result_timeout_s is None:
        result_timeout_s = frontend.sconf.deadline_s + 30.0
    pending: _stdqueue.Queue = _stdqueue.Queue()
    n = 0

    def _write_loop():
        while True:
            fut = pending.get()
            if fut is None:
                return
            try:
                res = fut.result(result_timeout_s)
            except TimeoutError:
                res = ServeResult(ERROR, -1, -1, detail="result-timeout")
            try:
                wfile.write(res.encode() + "\n")
                wfile.flush()
            except (OSError, ValueError):
                # client gone: keep draining futures so submitters and
                # batcher completions are not stranded, drop the writes
                continue

    writer = threading.Thread(target=_write_loop, daemon=True,
                              name="dos-serve-writer")
    writer.start()
    if families is not None:       # once, not per line on the hot loop
        from ..traffic.families import parse_family_line
    try:
        for line in rfile:
            body = line.strip()
            if not body or body.startswith("#"):
                continue
            if body == QUIT_TOKEN:
                break
            if families is not None:
                try:
                    fam = parse_family_line(body)
                except ValueError:
                    pending.put(Future.completed(ServeResult(
                        ERROR, -1, -1, detail="malformed-line")))
                    continue
                if fam is not None:
                    try:
                        fut = families.submit_line(*fam)
                    except Exception as e:  # noqa: BLE001 — a bad
                        # family request (out-of-range node, missing
                        # graph) must answer in-order like a malformed
                        # line, never kill the whole session
                        detail = (str(e).split("\n")[0]
                                  .replace(" ", "-") or "family-failed")
                        pending.put(Future.completed(ServeResult(
                            ERROR, -1, -1, detail=detail)))
                        continue
                    pending.put(fut)
                    n += 1
                    continue
            try:
                s, t = parse_query_line(body)
            except ValueError:
                pending.put(Future.completed(ServeResult(
                    ERROR, -1, -1, detail="malformed-line")))
                continue
            pending.put(frontend.submit(s, t))
            n += 1
    finally:
        pending.put(None)
        writer.join(timeout=result_timeout_s + 5.0)
    return n


def serve_stdin(frontend: ServingFrontend, families=None) -> int:
    import sys

    return serve_stream(frontend, sys.stdin, sys.stdout,
                        families=families)


def serve_unix_socket(frontend: ServingFrontend, path: str,
                      stop: threading.Event | None = None,
                      families=None) -> None:
    """Accept loop on a unix stream socket; one ``serve_stream`` session
    per connection. Bounded accept timeout so ``stop`` (or KeyboardInterrupt)
    is honored promptly; connection threads are joined on exit."""
    stop = stop or threading.Event()
    if os.path.exists(path):
        os.remove(path)
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(path)
    srv.listen(16)
    srv.settimeout(0.25)
    log.info("serving line protocol on unix socket %s", path)
    conns: list[threading.Thread] = []

    def _session(sock: socket.socket) -> None:
        with sock:
            rfile = sock.makefile("r")
            wfile = sock.makefile("w")
            try:
                serve_stream(frontend, rfile, wfile,
                             families=families)
            except Exception as e:  # noqa: BLE001 — one bad client
                # must not kill the accept loop
                log.warning("socket session failed: %s", e)

    try:
        while not stop.is_set():
            try:
                sock, _ = srv.accept()
            except socket.timeout:
                continue
            th = threading.Thread(target=_session, args=(sock,),
                                  daemon=True, name="dos-serve-conn")
            th.start()
            # prune finished sessions so a long-lived service doesn't
            # accumulate one dead Thread per connection forever
            conns = [t for t in conns if t.is_alive()]
            conns.append(th)
    finally:
        srv.close()
        if os.path.exists(path):
            os.remove(path)
        for th in conns:
            th.join(timeout=5.0)


def tail_file(frontend: ServingFrontend, path: str,
              out_path: str | None = None,
              stop: threading.Event | None = None,
              poll_s: float = 0.2, families=None) -> int:
    """Follow ``path`` for appended request lines (the dead-simple
    ingress for batch producers that can only write files); responses
    append to ``<path>.answers``. A ``quit`` line ends the tail."""
    stop = stop or threading.Event()
    out_path = out_path or path + ".answers"
    n = 0
    with open(out_path, "a") as wfile:
        # wait for the input to exist so an operator can start the
        # server before the producer
        while not os.path.exists(path):
            if stop.is_set():
                return 0
            time.sleep(poll_s)
        with open(path) as rfile:

            def _lines():
                while not stop.is_set():
                    line = rfile.readline()
                    if not line:
                        time.sleep(poll_s)
                        continue
                    if not line.endswith("\n"):
                        # partial write: wait for the rest of the line
                        while (not line.endswith("\n")
                               and not stop.is_set()):
                            chunk = rfile.readline()
                            if not chunk:
                                time.sleep(poll_s)
                                continue
                            line += chunk
                    yield line

            n = serve_stream(frontend, _lines(), wfile,
                             families=families)
    return n
