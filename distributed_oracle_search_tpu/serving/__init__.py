"""Online serving layer: request queues, adaptive micro-batching, and a
result cache on top of the shard oracle.

The campaign drivers (``cli.process_query``) answer a *closed* workload:
a whole scenario file partitioned once, one batch per worker per diff
round, then exit. This package is the *open*-workload shape — the
standard online-inference frontend (continuous/adaptive batching a la
Orca / Clipper-style prediction-serving), built on the transport,
resilience, and observability layers the campaign path already uses:

* :class:`~.frontend.ServingFrontend` — accepts single ``s t`` queries,
  routes each to its target-owner shard via the
  ``DistributionController``, and applies admission control: a full
  per-shard queue sheds ``BUSY``, an OPEN circuit breaker sheds
  ``UNAVAILABLE`` — never a silent hang;
* :class:`~.queue.ShardQueue` — bounded per-shard request queue with
  per-request deadlines (expired requests complete ``TIMEOUT``);
* :class:`~.batcher.MicroBatcher` — per-shard adaptive micro-batcher:
  flushes when the batch hits the power-of-two ``max_batch`` (so
  workers reuse the handful of compiled programs ``ShardEngine`` keys
  on ``qpad``) or when ``max_wait_ms`` elapses, and keeps exactly ONE
  batch in flight per shard so host-side batch forming pipelines with
  device execution;
* :class:`~.cache.ResultCache` — bounded LRU keyed on
  ``(s, t, diff, knob fingerprint)``, short-circuiting repeats on
  skewed traffic; invalidated on diff change;
* :mod:`~.dispatch` — the shard backends: in-process
  :class:`~.dispatch.EngineDispatcher` (one ``ShardEngine`` per shard)
  and :class:`~.dispatch.FifoDispatcher` (the campaign wire +
  ``transport.send_with_retry``, per-query answers returned via the
  ``RuntimeConfig.results`` sidecar extension);
* :mod:`~.ingress` — the line protocol (stdin / unix socket /
  file-tail): one ``s t`` per line in, one result line out, responses
  in request order.

With shard replication (``DOS_REPLICATION`` / conf ``replication`` >
1) the frontend is replica-aware: admission sheds ``UNAVAILABLE`` only
when EVERY replica of the target shard is breaker-dead, dispatch fails
over to the next live replica (``failover_total``), and slow batches
are hedged — a duplicate to a replica after the shard's adaptive
latency-quantile delay, first answer wins, bounded by a hedge-rate
budget (:mod:`~.hedge`, ``DOS_HEDGE_*`` knobs).

Entry point: ``python -m distributed_oracle_search_tpu.cli.serve``
(``dos-serve``). Env knobs: ``DOS_SERVE_QUEUE_DEPTH``,
``DOS_SERVE_MAX_BATCH``, ``DOS_SERVE_MAX_WAIT_MS``,
``DOS_SERVE_CACHE_BYTES``, ``DOS_SERVE_DEADLINE_MS`` (see
:class:`~.config.ServeConfig`); ``DOS_HEDGE_QUANTILE``,
``DOS_HEDGE_MIN_MS``, ``DOS_HEDGE_BUDGET``, ``DOS_HEDGE_WINDOW``,
``DOS_HEDGE_DISABLE`` (see :class:`~.hedge.HedgeConfig`).
"""

from .batcher import MicroBatcher
from .cache import ResultCache, knob_fingerprint
from .config import ServeConfig
from .dispatch import (
    AutoDispatcher, CallableDispatcher, DispatchError, EngineDispatcher,
    FifoDispatcher, RpcDispatcher, RpcUnavailableError,
)
from .frontend import ServingFrontend
from .hedge import HedgeConfig, HedgeTracker
from .queue import ShardQueue
from .request import (
    BUSY, ERROR, Future, OK, ServeRequest, ServeResult, TIMEOUT,
    UNAVAILABLE,
)

__all__ = [
    "AutoDispatcher", "BUSY", "CallableDispatcher", "DispatchError",
    "ERROR", "EngineDispatcher", "FifoDispatcher", "Future",
    "HedgeConfig", "RpcDispatcher", "RpcUnavailableError",
    "HedgeTracker", "MicroBatcher", "OK",
    "ResultCache", "ServeConfig", "ServeRequest", "ServeResult",
    "ServingFrontend", "ShardQueue", "TIMEOUT", "UNAVAILABLE",
    "knob_fingerprint",
]
