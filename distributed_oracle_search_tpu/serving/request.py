"""Request/result types of the online path + a minimal future.

Statuses are the line protocol's first token and carry the shed
semantics the frontend guarantees:

* ``OK`` — answered; ``cost plen finished`` follow.
* ``BUSY`` — shed at admission: the target shard's bounded queue is
  full. The client should back off and retry; nothing was enqueued.
* ``UNAVAILABLE`` — shed at admission: the target shard's circuit
  breaker is OPEN (worker dead/sick, ``transport.resilience``) or the
  frontend is shutting down. Retrying immediately will keep failing
  until the breaker's probes heal it.
* ``TIMEOUT`` — admitted, but the per-request deadline expired before
  the batch dispatched (overload deeper than the queue bound).
* ``ERROR`` — dispatch ran and failed (engine exception, wire failure,
  malformed input).

Every submitted request terminates in exactly one of these — an
overloaded or broken serving path answers, it never hangs.
"""

from __future__ import annotations

import dataclasses
import threading

OK = "OK"
BUSY = "BUSY"
UNAVAILABLE = "UNAVAILABLE"
TIMEOUT = "TIMEOUT"
ERROR = "ERROR"

#: statuses shed at admission (nothing was enqueued)
SHED = (BUSY, UNAVAILABLE)


class Future:
    """Single-assignment result slot (threading.Event based — no
    executor machinery; the batcher threads call :meth:`set` exactly
    once per request)."""

    __slots__ = ("_ev", "_result")

    def __init__(self):
        self._ev = threading.Event()
        self._result = None

    def set(self, result: "ServeResult") -> None:
        self._result = result
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: float | None = None) -> "ServeResult":
        if not self._ev.wait(timeout):
            raise TimeoutError("serve request still pending")
        return self._result

    @classmethod
    def completed(cls, result: "ServeResult") -> "Future":
        f = cls()
        f.set(result)
        return f


@dataclasses.dataclass
class ServeResult:
    """One request's terminal answer (see module docstring for the
    status semantics). ``t_done`` is the completion monotonic timestamp
    (stamped by the frontend) so open-loop load generators can measure
    per-request latency without wrapping every future."""

    status: str
    s: int
    t: int
    cost: int = 0
    plen: int = 0
    finished: bool = False
    cached: bool = False
    detail: str = ""
    t_done: float = 0.0

    @property
    def ok(self) -> bool:
        return self.status == OK

    def encode(self) -> str:
        """Line-protocol response: ``OK <s> <t> <cost> <plen>
        <finished> [cached]`` or ``<STATUS> <s> <t> [detail]``."""
        if self.status == OK:
            line = (f"OK {self.s} {self.t} {self.cost} {self.plen} "
                    f"{int(self.finished)}")
            return line + " cached" if self.cached else line
        line = f"{self.status} {self.s} {self.t}"
        return f"{line} {self.detail}" if self.detail else line


@dataclasses.dataclass
class ServeRequest:
    """One admitted in-flight request. ``t_submit`` anchors the
    end-to-end latency histogram; ``t_enqueue`` (stamped by the queue)
    anchors the batcher's time-to-flush; ``deadline`` is absolute
    monotonic time after which dispatch completes the request
    ``TIMEOUT`` instead of running it."""

    s: int
    t: int
    wid: int
    key: tuple
    t_submit: float
    deadline: float | None = None
    future: Future = dataclasses.field(default_factory=Future)
    t_enqueue: float = 0.0
    #: the batch trace id this request dispatched under (stamped by the
    #: frontend when tracing is on) — the exemplar key that links a bad
    #: latency observation to its Perfetto timeline
    trace_id: str = ""

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline
