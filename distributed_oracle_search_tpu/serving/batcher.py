"""Per-shard adaptive micro-batcher: two threads, one batch in flight.

The **collector** thread forms batches off the shard's
:class:`~.queue.ShardQueue` (flush at the power-of-two ``max_batch`` or
on ``max_wait_ms`` expiry) and hands them through a depth-1 queue to the
**runner** thread, which executes the dispatch callback. The depth-1
handoff is the pipelining contract: exactly ONE batch is in flight on
the shard while the collector is already forming (and the frontend's
dispatch callback is host-prepping) the next — and when the shard falls
behind, the handoff's backpressure makes waiting batches grow toward
``max_batch`` instead of racing out as singletons, which is what makes
the batching *adaptive*: batch size tracks load.

Threads are named ``dos-serve-*`` — the test suite's leak check
(tests/conftest.py) holds every ``dos-*`` thread to the
joined-on-shutdown contract, and :meth:`MicroBatcher.stop` joins both.
"""

from __future__ import annotations

import queue as _stdqueue
import threading
import time

from ..obs import metrics as obs_metrics
from ..utils.log import get_logger
from .queue import ShardQueue
from .request import ERROR, ServeRequest, ServeResult

log = get_logger(__name__)

M_BATCHES = obs_metrics.counter(
    "serve_batches_total", "batches dispatched by the micro-batchers")
M_FLUSH_FULL = obs_metrics.counter(
    "serve_flush_full_total", "flushes triggered by max_batch")
M_FLUSH_WAIT = obs_metrics.counter(
    "serve_flush_wait_total", "flushes triggered by max_wait_ms expiry")
# dos-lint: disable=metric-registry -- serve_batch_fill is a
#   dimensionless batch-SIZE histogram, not a latency: the power-of-two
#   buckets are the unit, a _seconds suffix would misdescribe it
H_FILL = obs_metrics.histogram(
    "serve_batch_fill", "dispatched batch size (requests)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
H_FLUSH = obs_metrics.histogram(
    "serve_time_to_flush_seconds",
    "first request enqueued until its batch flushed")
H_DISPATCH = obs_metrics.histogram(
    "serve_dispatch_seconds", "batch dispatch (engine call or wire "
    "round-trip) as seen by the runner thread")
G_INFLIGHT = obs_metrics.gauge(
    "serve_batches_in_flight", "batches currently executing")


class MicroBatcher:
    """One shard's batcher. ``dispatch(batch)`` must complete every
    request's future; the runner backstops a raising dispatch so no
    future is ever left pending."""

    def __init__(self, wid: int, shard_queue: ShardQueue, dispatch,
                 max_batch: int, max_wait_s: float):
        self.wid = wid
        self.queue = shard_queue
        self.dispatch = dispatch
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self._handoff: _stdqueue.Queue = _stdqueue.Queue(maxsize=1)
        self._stop = threading.Event()
        #: THIS batcher's dispatch-in-progress flag — stop() must drain
        #: on it, not on the process-global in-flight gauge, or one busy
        #: shard (or a second frontend) would stall every other shard's
        #: shutdown for the full drain budget
        self._dispatching = False
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True,
            name=f"dos-serve-collect-w{wid}")
        self._runner = threading.Thread(
            target=self._run_loop, daemon=True,
            name=f"dos-serve-dispatch-w{wid}")

    def start(self) -> None:
        self._collector.start()
        self._runner.start()

    # ---------------------------------------------------------- threads
    def _collect_loop(self) -> None:
        while True:
            batch = self.queue.get_batch(self.max_batch, self.max_wait_s,
                                         self._stop)
            if not batch:
                # a closed, drained queue is terminal (try_put refuses
                # once closed): exit instead of spinning on instant
                # empty get_batch returns until stop() gets to us
                if self._stop.is_set() or self.queue.closed:
                    return
                continue
            H_FILL.observe(len(batch))
            H_FLUSH.observe(time.monotonic() - batch[0].t_enqueue)
            (M_FLUSH_FULL if len(batch) >= self.max_batch
             else M_FLUSH_WAIT).inc()
            while True:
                try:
                    self._handoff.put(batch, timeout=_HANDOFF_TICK_S)
                    break
                except _stdqueue.Full:
                    if self._stop.is_set():
                        _fail_batch(batch, "shutdown")
                        return

    def _run_loop(self) -> None:
        while True:
            try:
                batch = self._handoff.get(timeout=_HANDOFF_TICK_S)
            except _stdqueue.Empty:
                if self._stop.is_set():
                    return
                continue
            self._dispatching = True
            G_INFLIGHT.add(1)
            t0 = time.perf_counter()
            try:
                self.dispatch(batch)
            except Exception as e:  # noqa: BLE001 — a dispatch bug must
                # never strand waiters or kill the shard's runner
                log.exception("shard w%d batch dispatch raised: %s",
                              self.wid, e)
            finally:
                G_INFLIGHT.add(-1)
                self._dispatching = False
                H_DISPATCH.observe(time.perf_counter() - t0)
                M_BATCHES.inc()
                _fail_batch(batch, "dispatch-raised")  # only undone ones

    # --------------------------------------------------------- shutdown
    def stop(self, drain_s: float = 5.0) -> None:
        """Close the queue, give in-flight/queued work ``drain_s`` to
        finish, then stop both threads and fail anything left — every
        admitted request still terminates."""
        self.queue.close()
        deadline = time.monotonic() + max(drain_s, 0.0)
        while time.monotonic() < deadline:
            if (len(self.queue) == 0 and self._handoff.empty()
                    and not self._dispatching):
                break
            time.sleep(0.01)
        self._stop.set()
        for t in (self._collector, self._runner):
            if t.is_alive():
                t.join(timeout=drain_s + 1.0)
        _fail_batch(self.queue.drain(), "shutdown")
        while True:
            try:
                _fail_batch(self._handoff.get_nowait(), "shutdown")
            except _stdqueue.Empty:
                break


#: wakeup tick for the depth-1 handoff waits (stop-signal latency bound)
_HANDOFF_TICK_S = 0.05


def _fail_batch(batch: list[ServeRequest], detail: str) -> None:
    """Complete every still-pending request with ERROR (idempotent:
    completed futures are skipped)."""
    now = time.monotonic()
    for r in batch:
        if not r.future.done():
            r.future.set(ServeResult(ERROR, r.s, r.t, detail=detail,
                                     t_done=now))
