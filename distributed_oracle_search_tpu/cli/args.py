"""Shared CLI parser for all drivers.

Flag-surface parity with the reference's shared argparse namespace
(reference ``args.py:1-188``), minus its dead flags: the Spark interface /
broadcast / streaming / server groups are vestiges of an architecture the
reference itself migrated off (reference ``offline.py:1-3``, SURVEY.md §5
"config") and are deliberately not reproduced.

Differences from the reference:

* built as a function returning a fresh parser (the reference parses at
  import time into a module global, ``args.py:188`` — untestable);
* ``parse_known_args`` pass-through is preserved via ``parse_args(argv)``
  wrapper below;
* new ``--backend {tpu,host}`` override and ``--profile`` (jax.profiler
  trace dir).
"""

from __future__ import annotations

import argparse
import os


def build_parser(prog: str | None = None) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=prog, conflict_handler="resolve")
    p.add_argument("-v", "--verbose", action="count", default=0)
    p.add_argument("-t", "--test", action="store_true",
                   help="Run the canned smoke-test config.")
    p.add_argument("-c", type=str, default="./example-cluster-conf.json",
                   help="Cluster config JSON.")
    p.add_argument("-D", "--debug", action="store_true",
                   help="Deterministic single-threaded repro mode.")
    p.add_argument("-w", "--worker", type=int, default=-1,
                   help="Restrict the run to one worker id.")

    part = p.add_argument_group("partitioning")
    part.add_argument("-p", "--num-partitions", type=int, default=0,
                      help="Number of partitions (0 = one per worker).")
    part.add_argument("-s", "--size-partitions", type=int, default=0,
                      help="Target partition size (overrides -p).")
    part.add_argument("--group", type=str,
                      choices=["all", "mod", "div"],
                      help="Partition generation scheme; default is by "
                           "range.")
    part.add_argument("--sort", action="store_true",
                      help="Sort partitions on targets before sending.")
    modus = part.add_mutually_exclusive_group()
    modus.add_argument("--div", type=int,
                       help="Assign nodes to worker = target / div.")
    modus.add_argument("--mod", type=int,
                       help="Assign nodes to worker = target %% mod.")
    modus.add_argument("--alloc", type=int, nargs="+",
                       help="Ascending range bounds, one per worker.")

    path = p.add_argument_group("search")
    path.add_argument("-k", "--k-moves", type=int, default=-1,
                      help="Number of moves to extract; -1 = all.")
    path.add_argument("--extract", action="store_true",
                      help="Materialize each query's first k-moves path "
                           "nodes (needs -k > 0): workers write "
                           "<queryfile>.paths, the campaign collects "
                           "paths.csv. Wire extension; the reference "
                           "computed prefixes but never returned them.")
    path.add_argument("--h-scale", default=1.0, type=float,
                      help="Heuristic tolerance factor for A*.")
    path.add_argument("--f-scale", default=0.0, type=float,
                      help="Sub-optimality factor for A*.")
    path.add_argument("--itrs", default=1, type=int,
                      help="Search iterations per batch.")
    path.add_argument("--s-lim", default=0, type=int,
                      help="Time limit in seconds.")
    path.add_argument("--ms-lim", default=0, type=int,
                      help="Time limit in milliseconds.")
    path.add_argument("--us-lim", default=0, type=int,
                      help="Time limit in microseconds.")
    path.add_argument("--ns-lim", default=0, type=int,
                      help="Time limit in nanoseconds.")

    batch = p.add_argument_group("batching")
    batch.add_argument("-o", "--output",
                       help="Directory to write campaign artifacts to.")
    batch.add_argument("--omp", type=int, default=0,
                       help="Worker thread count (wire parity; XLA SPMD "
                            "makes this a no-op on device).")

    files = p.add_argument_group("files")
    files.add_argument("-b", "--base", type=str, default=".",
                       help="Base directory the code is run from.")
    files.add_argument("-d", "--dir", type=str, default="data",
                       help="Directory containing map/scenario files.")
    files.add_argument("-m", "--map", type=str, default="",
                       help="Graph (.xy) to use.")
    files.add_argument("--scenario", type=str, default="",
                       help="Scenario file to read from.")
    files.add_argument("--diff", type=str,
                       help="Travel-time diff file for the search.")
    files.add_argument("--order", type=str, default=None,
                       help="Node ordering: bfs | rcm | order-file "
                            "(reference args.py:119 NodeOrdering). "
                            "Datasets are reordered up front by "
                            "cli.reorder; this flag names the ordering "
                            "that produced them.")

    rand = p.add_argument_group("random")
    rand.add_argument("-R", "--random", action="store_true",
                      help="Randomise the seed.")
    rand.add_argument("--seed", type=int, default=562410645)

    fifo = p.add_argument_group("fifo")
    fifo.add_argument("--fifo", type=str, default="/tmp/warthog.fifo",
                      help="Command FIFO path (offline/local mode).")
    fifo.add_argument("--local", action="store_true",
                      help="Force the local no-ssh path.")
    fifo.add_argument("--cutoff", type=int, default=0,
                      help="Below this many queries, run locally.")
    fifo.add_argument("--thread-alloc", type=int, default=0,
                      help="Receiver-thread pinning (wire parity no-op).")
    fifo.add_argument("--nfs", type=str, default="/tmp",
                      help="Shared directory for query files.")
    fifo.add_argument("--diffs", type=str, nargs="+", default=["-"],
                      help="Diff files for congestion; '-' = free flow.")
    fifo.add_argument("--no-cache", action="store_true",
                      help="Disable the workers' runtime cache.")
    fifo.add_argument("--supervise", action="store_true",
                      help="make_fifos: stay resident as a worker "
                           "supervisor — launch the servers as "
                           "subprocesses, ping them via the "
                           "__DOS_PING__ liveness frame, and respawn "
                           "crashed ones with capped exponential "
                           "backoff (local hosts only; see "
                           "worker.supervisor).")
    fifo.add_argument("--traffic-dir", default=None,
                      help="make_fifos --supervise: diff segment "
                           "stream directory passed to every spawned "
                           "worker.server, so supervised workers gate "
                           "requests from diff epochs their filesystem "
                           "view has not seen yet (STALE_DIFF) instead "
                           "of failing the fused-file open.")
    fifo.add_argument("--alg", default="table-search",
                      choices=["table-search", "astar", "ch"],
                      help="Serving algorithm — honored by BOTH backends "
                           "(host servers via make_fifos, and the "
                           "in-process TPU campaign). The reference "
                           "hard-codes table-search (make_fifos.py:20); "
                           "astar serves the hscale/fscale family "
                           "(batched device kernel in TPU mode), ch the "
                           "congestion-free contraction hierarchy "
                           "(native engine only).")

    new = p.add_argument_group("tpu (new in this framework)")
    new.add_argument("--backend", choices=["auto", "tpu", "host"],
                     default="auto",
                     help="Execution backend; auto follows the cluster "
                          "conf's partmethod.")
    new.add_argument("--profile", type=str, default="",
                     help="Write a jax.profiler trace to this directory.")
    new.add_argument("--chunk", type=int, default=0,
                     help="CPD build: target rows per build step "
                          "(0 = all owned rows at once).")
    new.add_argument("--no-resume", action="store_true",
                     help="make_cpds: rebuild every block from scratch "
                          "instead of resuming off the per-worker build "
                          "ledger (default: resume — only blocks whose "
                          "ledger digest no longer matches the file are "
                          "recomputed).")
    new.add_argument("--delta-from", type=str, default=None,
                     metavar="OLD_INDEX",
                     help="make_cpds: DELTA rebuild — given this "
                          "existing index plus a fused diff (--diff), "
                          "recompute only the rows whose first-move "
                          "entries can change (tense-edge pass), byte-"
                          "copy untouched blocks, and write an epoch-"
                          "tagged index under OLD_INDEX/epoch-e<N> "
                          "that the serve path can promote without "
                          "restart. Bit-identical to a from-scratch "
                          "build on the retimed graph.")
    new.add_argument("--delta-epoch", type=int, default=None,
                     help="diff epoch tag for --delta-from (default: "
                          "parsed from the fused diff's "
                          "fused-e<N>.diff name, else the old "
                          "manifest's diff_epoch + 1).")
    new.add_argument("--verify", action="store_true",
                     help="make_cpds: check-only integrity pass over the "
                          "conf's index — every manifest block is digest/"
                          "shape-verified in place; exits 0 clean, 3 "
                          "degraded (some blocks bad), 4 corrupt (no "
                          "usable manifest or no block survived), "
                          "mirroring process_query's exit codes.")
    new.add_argument("--scrub", action="store_true",
                     help="make_cpds: at-rest scrub cadence — repeat "
                          "the --verify check-only pass every "
                          "--scrub-interval seconds for --scrub-passes "
                          "passes, exiting with the WORST pass code "
                          "(0 clean / 3 degraded / 4 corrupt). The "
                          "offline counterpart of the serve-side "
                          "resident scrubber (DOS_SCRUB_INTERVAL_S).")
    new.add_argument("--scrub-interval", type=float, default=60.0,
                     help="--scrub: seconds between passes "
                          "(default 60).")
    new.add_argument("--scrub-passes", type=int, default=1,
                     help="--scrub: number of passes; 0 repeats until "
                          "interrupted (default 1).")
    new.add_argument("--engine", choices=["python", "native"],
                     default="python",
                     help="Host-mode worker engine: the JAX shard engine "
                          "or the native C++ binaries (./install.sh).")
    new.add_argument("--codec", choices=["raw", "pack4", "rle", "auto"],
                     default=None,
                     help="make_cpds: persist CPD blocks compressed "
                          "(models.resident RLE/pack4 containers; "
                          "per-block degrade to raw when not viable). "
                          "Default: the DOS_CPD_RESIDENT knob, whose "
                          "raw default keeps the legacy block format.")

    obs = p.add_argument_group("observability")
    obs.add_argument("--trace", type=str, default="",
                     help="Write a merged Chrome trace-event JSON of the "
                          "campaign's head + worker spans to this path "
                          "(open in Perfetto or chrome://tracing); the "
                          "per-batch trace_id rides the FIFO wire as a "
                          "RuntimeConfig extension.")
    obs.add_argument("--metrics-dump", type=str, default="",
                     help="Write a JSON snapshot of the obs.metrics "
                          "registry (counters / gauges / histograms) to "
                          "this path at campaign end.")
    obs.add_argument("--obs-port", type=int, default=None,
                     help="Serve live /metrics /healthz /statusz scrape "
                          "endpoints on this port for the process's "
                          "lifetime (0 = OS-assigned; default off; "
                          "DOS_OBS_PORT env).")
    return p


def parse_args(argv=None, prog: str | None = None) -> argparse.Namespace:
    """Parse, tolerating unknown flags (parity with the reference's
    ``parse_known_args`` pass-through, ``args.py:188``)."""
    args, _unknown = build_parser(prog).parse_known_args(argv)
    return args


def get_time_ns(args) -> int:
    """Resolve the ``--s/ms/us/ns-lim`` family to one ns budget (parity:
    reference ``args.py:210-221``)."""
    tlim = args.ns_lim
    if args.s_lim > 0:
        tlim = int(args.s_lim * 1e9)
    elif args.ms_lim > 0:
        tlim = int(args.ms_lim * 1e6)
    elif args.us_lim > 0:
        tlim = int(args.us_lim * 1e3)
    return tlim


def process_filename(fname: str, base: str = ".", dirname: str = "") -> str:
    """Resolve a data filename directly or under ``base/dir`` (parity:
    reference ``args.py:198-207``)."""
    if os.path.isfile(fname):
        return fname
    with_dir = os.path.join(base, dirname, fname)
    if os.path.isfile(with_dir):
        return with_dir
    raise IOError(f"File {fname} not found, searched {with_dir}.")
