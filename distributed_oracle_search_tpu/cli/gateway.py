"""Gateway tier: N stateless binary-protocol frontends (``dos-gateway``).

Where ``dos-serve`` keeps ONE :class:`~..serving.ServingFrontend`
behind a line-protocol ingress, this entry point runs a horizontal
tier: ``--replicas`` frontends in one process, each with its own
admission/batcher/hedge stack over the SAME worker pool, each listening
on its own unix socket speaking the binary gateway protocol
(:mod:`..gateway.protocol` — multiplexed batched query frames for all
families, credit-window backpressure, hello version negotiation).
Replicas share nothing but ``membership.json`` and the diff-epoch
spool, so killing one loses no state — clients reconnect to a sibling.

Clients use :class:`~..gateway.DosClient`; sockets land at
``<socket-dir>/dos-gateway-f<fid>.sock``. Knobs come from
``DOS_GATEWAY_*`` env vars, overridable by flags. ``--obs-port`` serves
``/statusz`` with a ``gateway`` section (per-replica client counts and
L1 hit rates) that ``dos-obs top`` renders as the tier's columns.

High availability: ``--registry-dir`` (default: the conf's index
directory) points at the leased endpoint registry ``gateway.json``
(:mod:`..gateway.registry`) — every replica registers its socket there
and renews on a heartbeat, so clients discover and fail over by
reading the file. ``--join`` claims fresh frontend ids ABOVE whatever
the registry has seen, letting a second ``dos-gateway --join`` process
(same registry, same worker pool) widen the tier horizontally: one
logical tier spanning processes, bit-identical answers from every
replica.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from ..gateway import GatewayConfig, GatewayTier
from ..obs import metrics as obs_metrics
from ..utils.log import get_logger, set_verbosity

log = get_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gateway", description=__doc__.splitlines()[0])
    p.add_argument("-c", default="./example-cluster-conf.json",
                   help="cluster config JSON")
    p.add_argument("-t", "--test", action="store_true",
                   help="serve the canned synthetic dataset (builds "
                        "missing CPD shards in-process)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    p.add_argument("--backend", default="inproc",
                   choices=["inproc", "host"],
                   help="inproc: shard engines in this process; host: "
                        "FIFO/RPC wire to resident worker servers")
    p.add_argument("--alg", default="table-search",
                   choices=["table-search", "astar"])
    p.add_argument("--diff", default=None,
                   help="active congestion diff (default: the conf's "
                        "first diff, '-' = free flow)")
    p.add_argument("--replicas", type=int, default=None,
                   help="frontend replica count (DOS_GATEWAY_REPLICAS)")
    p.add_argument("--socket-dir", default=None,
                   help="where replica sockets land "
                        "(DOS_GATEWAY_SOCKET_DIR)")
    p.add_argument("--credit", type=int, default=None,
                   help="per-connection credit window "
                        "(DOS_GATEWAY_CREDIT)")
    p.add_argument("--registry-dir", default=None,
                   help="leased endpoint registry directory holding "
                        "gateway.json (default: the conf's index "
                        "directory)")
    p.add_argument("--lease-s", type=float, default=None,
                   help="endpoint lease TTL seconds "
                        "(DOS_GATEWAY_LEASE_S)")
    p.add_argument("--join", action="store_true",
                   help="join an existing tier: claim fresh frontend "
                        "ids from the registry instead of starting at "
                        "f0 (replicas spanning processes)")
    p.add_argument("--queue-depth", type=int, default=None,
                   help="per-shard queue bound (DOS_SERVE_QUEUE_DEPTH)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="micro-batch flush size (DOS_SERVE_MAX_BATCH)")
    p.add_argument("--max-wait-ms", type=float, default=None,
                   help="micro-batch wait bound (DOS_SERVE_MAX_WAIT_MS)")
    p.add_argument("--cache-bytes", type=int, default=None,
                   help="per-replica L1 result-cache budget, 0 disables "
                        "(DOS_SERVE_CACHE_BYTES)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request deadline (DOS_SERVE_DEADLINE_MS)")
    p.add_argument("--traffic-dir", default=None,
                   help="diff segment stream directory (live epoch "
                        "swaps; scoped L1 invalidation per replica)")
    p.add_argument("--traffic-spool", default=None,
                   help="fused per-epoch diff spool (shared with "
                        "workers for --backend host)")
    p.add_argument("--metrics-dump", default="",
                   help="write a JSON metrics snapshot here on shutdown")
    p.add_argument("--obs-port", type=int, default=None,
                   help="serve /metrics /healthz /statusz on this port "
                        "(0 = ephemeral; default off; DOS_OBS_PORT)")
    p.add_argument("--recorder-dir", default=None,
                   help="flight-recorder tape directory "
                        "(DOS_RECORDER_DIR; default off)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    set_verbosity(args.verbose)
    if args.test:
        import os

        from ..data.synth import ensure_synth_dataset
        from ..utils.config import test_config

        conf = test_config()
        ensure_synth_dataset(os.path.dirname(conf.xy_file) or "./data")
    else:
        from ..utils.config import ClusterConfig

        conf = ClusterConfig.load(args.c)
    gconf = GatewayConfig.from_env(
        replicas=args.replicas, socket_dir=args.socket_dir,
        credit=args.credit, lease_s=args.lease_s)
    # the leased endpoint registry lives beside membership.json unless
    # pointed elsewhere; every replica leases its socket there so
    # clients discover/fail over and the control loop sees death
    from ..gateway import GatewayRegistry
    reg_dir = args.registry_dir or getattr(conf, "outdir", None)
    endpoint_registry = (GatewayRegistry(reg_dir, lease_s=gconf.lease_s)
                         if reg_dir else None)
    fid_base = 0
    if args.join:
        if endpoint_registry is None:
            log.error("--join needs a registry directory (the conf has "
                      "no index dir; pass --registry-dir)")
            return 2
        fid_base = endpoint_registry.claim(gconf.replicas,
                                           endpoint_of=gconf.socket_of)
    # each replica is a full serving stack from the SAME builder
    # dos-serve uses — admission, micro-batcher, hedging, breakers,
    # membership refresh, live-traffic epoch pump — so gateway replicas
    # and the single-head line-protocol serve stay behaviorally
    # identical per request
    from . import serve as serve_cli
    replicas = []
    registries = []
    for i in range(gconf.replicas):
        frontend, registry, families = serve_cli.build_frontend(
            conf, args)
        frontend.start()
        replicas.append((frontend, families))
        if registry is not None:
            registries.append(registry)
        log.info("frontend replica %d up (%s backend)", fid_base + i,
                 args.backend)
    tier = GatewayTier(replicas, gconf=gconf,
                       registry=endpoint_registry, fid_base=fid_base)
    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        if not stop_evt.is_set():
            log.info("received %s; draining the tier",
                     signal.Signals(signum).name)
        stop_evt.set()

    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _on_signal)
    obs_srv = recorder = None
    try:
        from ..obs import recorder as obs_recorder
        from ..obs.http import start_obs_server
        from ..utils.env import env_str

        rec_dir = args.recorder_dir or env_str("DOS_RECORDER_DIR")
        if rec_dir:
            recorder = obs_recorder.FlightRecorder(rec_dir)
            obs_recorder.set_recorder(recorder)
        tier.start()
        for ep in tier.endpoints:
            log.info("gateway listening at %s", ep)
        status_providers = {"gateway": tier.statusz}
        for fid, (fe, _fam) in enumerate(replicas):
            status_providers[f"serving_f{fid}"] = fe.statusz
        obs_srv = start_obs_server(
            args.obs_port,
            health_fn=lambda: {"ok": not stop_evt.is_set(),
                               "role": "dos-gateway",
                               "replicas": gconf.replicas},
            status_providers=status_providers)
        while not stop_evt.wait(0.5):
            pass
    except KeyboardInterrupt:
        log.info("interrupted; draining the tier")
    finally:
        stop_evt.set()
        tier.stop()
        for fe, _fam in replicas:
            fe.stop()
        if obs_srv is not None:
            obs_srv.close()
        if recorder is not None:
            from ..obs import recorder as obs_recorder
            obs_recorder.set_recorder(None)
            recorder.close()
        for registry in registries:
            registry.shutdown()
        if args.metrics_dump:
            obs_metrics.REGISTRY.dump_json(args.metrics_dump)
        log.info("gateway tier drained and stopped cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
