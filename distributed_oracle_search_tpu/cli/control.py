"""``dos-control``: run the closed-loop policy daemon standalone.

The daemon is normally *embedded* — ``dos-serve`` wires it to the
in-process frontend/breakers and ``dos-make-fifos --supervise`` to the
worker supervisor, where every actuator is live. Standalone mode
attaches from outside a running fleet with the handles that cross
process boundaries:

* **sense** — worker telemetry sidecars polled from the FIFO
  directory, SLO burn rates over the merged store, liveness probes on
  the FIFO wire;
* **act** — elastic membership moves (``plan_leave`` of a permanently
  dead worker operates on the shared ``membership.json``), scale
  advisories, and the full decision journal. In-process actuators
  (breaker pins, hedge/deadline brownout, respawn kicks) have no
  remote surface; a decision needing one is booked as an actuator
  error — visible, counted, and a reason to run embedded instead.

``--dry-run`` (or ``DOS_CONTROL_DRY_RUN=1``) books every decision
without executing anything. The daemon runs regardless of
``DOS_CONTROL`` here — invoking this CLI *is* the opt-in.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import time

from ..utils.config import ClusterConfig, test_config
from ..utils.log import get_logger, set_verbosity

log = get_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dos-control",
        description="closed-loop reconfiguration daemon (standalone)")
    p.add_argument("-c", default="cluster.conf",
                   help="cluster config (default cluster.conf)")
    p.add_argument("--test", action="store_true",
                   help="use the canned test config + synth dataset")
    p.add_argument("--fifo-dir", default=None,
                   help="worker FIFO/telemetry directory (default: "
                        "derived from the worker-0 command FIFO path)")
    p.add_argument("--interval", type=float, default=None,
                   help="tick cadence override (DOS_CONTROL_INTERVAL_S)")
    p.add_argument("--dry-run", action="store_true",
                   help="book decisions without executing")
    p.add_argument("--obs-port", type=int, default=None,
                   help="serve /metrics /statusz for the daemon itself")
    p.add_argument("--once", action="store_true",
                   help="run a single tick and exit (cron-style)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    set_verbosity(args.verbose)
    conf = test_config() if args.test else ClusterConfig.load(args.c)

    from ..control import ControlConfig, ControlDaemon
    from ..obs import slo as obs_slo
    from ..obs import telemetry as obs_telemetry
    from ..obs import timeseries as obs_timeseries
    from ..obs.http import start_obs_server
    from ..parallel import membership as fleet
    from ..transport import fifo as fifo_transport
    from ..transport.fifo import command_fifo_path

    cfg = ControlConfig.from_env()
    cfg = dataclasses.replace(
        cfg, enabled=True,
        dry_run=cfg.dry_run or args.dry_run,
        interval_s=(args.interval if args.interval is not None
                    else cfg.interval_s))
    cfg.validate()

    fifo_dir = (args.fifo_dir
                or os.path.dirname(command_fifo_path(0)) or ".")
    store = obs_timeseries.TimeseriesStore()
    ingest = obs_telemetry.TelemetryIngest(store)
    poller = obs_telemetry.SidecarPoller(fifo_dir, ingest).start()
    slo_engine = obs_slo.SLOEngine(store)
    from ..data.formats import xy_node_count
    from ..parallel.partition import DistributionController

    dc = DistributionController(conf.partmethod, conf.partkey,
                                conf.maxworker,
                                xy_node_count(conf.xy_file),
                                replication=conf
                                .effective_replication())
    mstate = fleet.load_state(conf.outdir)
    if mstate is not None:
        dc = fleet.apply_state(dc, mstate)
    mc = fleet.MembershipController(conf, dc)

    def probe_fn(wid: int) -> bool:
        try:
            host = mc.host_of(wid)
        except Exception as e:  # noqa: BLE001
            log.debug("probe: no roster host for w%d: %s", wid, e)
            return False
        st = fifo_transport.probe(host, wid,
                                  command_fifo=command_fifo_path(wid),
                                  nfs=conf.nfs)
        return st is not None and getattr(st, "ok", False)

    daemon = ControlDaemon(cfg, slo=slo_engine, membership=mc,
                           ingest=ingest, probe_fn=probe_fn)
    obs_srv = None
    try:
        if args.obs_port is not None:
            obs_srv = start_obs_server(
                args.obs_port,
                health_fn=lambda: {"ok": True, "role": "dos-control"},
                status_providers={"control": daemon.statusz})
        if args.once:
            daemon.tick()
            print(daemon.last_action or "no action")
            return 0
        daemon.start()
        print(f"dos-control up: interval={cfg.interval_s:.1f}s "
              f"dry_run={cfg.dry_run} fifo_dir={fifo_dir}; "
              "Ctrl-C to stop")
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        log.info("dos-control: interrupted")
    finally:
        daemon.stop()
        poller.stop()
        if obs_srv is not None:
            obs_srv.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
