"""Query campaign driver: the framework's ``process_query.py``.

Role parity with reference P4 (SURVEY.md §2.1, call stack §3.3): read the
scenario, partition queries by the worker owning each **target** node, run
one round per congestion diff, collect per-worker stats rows, and emit the
campaign artifacts.

Two backends behind one stats schema:

* ``partmethod=tpu`` — the north-star path: the CPD lives sharded on a
  device mesh; each diff round is answered by ONE sharded XLA call
  (``CPDOracle.query``) instead of N FIFO round-trips. Per-worker stats
  rows are recovered from the routed results, so downstream tooling sees
  the same ``parts.csv`` either way.
* host mode — the reference mechanism, modernized: query files to the
  shared dir, 2-line config through each worker's command FIFO, one CSV
  stats line back (``transport``), driven concurrently by a thread pool
  (reference ``process_query.py:180-185``), with explicit failure rows and
  retries instead of garbage rows (SURVEY.md §2.1 quirks).

Artifacts (``-o DIR``): ``metrics.json`` (phase timings), ``data.json``
(full arg dump), ``parts.csv`` (per-worker rows) — reference
``process_query.py:230-239``, with its multi-worker CSV crash fixed (the
reference's ``[[i] + row for i, row in stats]`` mis-unpacks, SURVEY.md §2.1).
"""

from __future__ import annotations

import csv
import json
import os
import sys

import numpy as np

from .args import get_time_ns, parse_args
from ..data.formats import read_diff, read_scen, xy_node_count
from ..parallel.partition import DistributionController
from ..transport.fifo import answer_fifo_path, command_fifo_path, fan_out
from ..transport.wire import (
    Request, RuntimeConfig, STATS_HEADER, StatsRow, write_query_file,
)
from ..transport import fifo as fifo_transport
from ..utils.config import ClusterConfig, test_config
from ..utils.log import get_logger, set_verbosity
from ..utils.timer import Timer

log = get_logger(__name__)


def runtime_config(args) -> RuntimeConfig:
    """Per-batch engine knobs from CLI args (parity: reference
    ``process_query.py:149-160``)."""
    return RuntimeConfig(
        hscale=args.h_scale, fscale=args.f_scale, time=get_time_ns(args),
        itrs=args.itrs, k_moves=args.k_moves, threads=args.omp,
        verbose=args.verbose, debug=args.debug,
        thread_alloc=args.thread_alloc, no_cache=args.no_cache,
    )


def effective_partition(conf: ClusterConfig, args):
    """CLI ``--div/--mod/--alloc`` override the conf's partmethod (the
    reference's modus group, ``args.py:175-183``)."""
    if args.div is not None:
        return "div", args.div
    if args.mod is not None:
        return "mod", args.mod
    if args.alloc is not None:
        return "alloc", list(args.alloc)
    return conf.partmethod, conf.partkey


# ------------------------------------------------------------------ TPU path

def run_tpu(conf: ClusterConfig, args, queries, dc, diffs):
    """All diff rounds in-process on the mesh; per-worker rows recovered
    from the routed results."""
    from ..data.graph import Graph
    from ..models.cpd import CPDOracle
    from ..parallel.mesh import make_mesh

    graph = Graph.from_xy(conf.xy_file)
    mesh = make_mesh(n_workers=conf.maxworker)
    oracle = CPDOracle(graph, dc, mesh=mesh)
    try:
        oracle.load(conf.outdir)
    except FileNotFoundError:
        log.info("no index at %s; building in-process", conf.outdir)
        oracle.build(chunk=args.chunk)
        oracle.save(conf.outdir)

    owner = dc.worker_of(queries[:, 1])
    stats = []
    for diff in diffs:
        with Timer() as prep:
            w_query = (None if diff == "-"
                       else graph.weights_with_diff(read_diff(diff)))
        with Timer() as search:
            cost, plen, fin = oracle.query(
                queries, w_query=w_query, k_moves=args.k_moves,
                active_worker=args.worker)
        rows = []
        for wid in range(dc.maxworker):
            if args.worker != -1 and wid != args.worker:
                continue
            mask = owner == wid
            size = int(mask.sum())
            if size == 0:
                continue
            row = StatsRow(
                n_expanded=int(plen[mask].sum()),
                n_touched=size,
                plen=int(plen[mask].sum()),
                finished=int(fin[mask].sum()),
                t_receive=prep.interval,
                t_astar=search.interval,
                t_search=search.interval,
            )
            rows.append(row.as_list(t_prepare=prep.interval,
                                    t_partition=0.0, size=size))
        stats.append(rows)
    return stats


# ----------------------------------------------------------------- host path

def send_queries(host: str, wid: int, part: np.ndarray, rconf: RuntimeConfig,
                 nfs: str, diff: str, t_partition: float = 0.0,
                 timeout: float | None = fifo_transport.DEFAULT_TIMEOUT
                 ) -> list:
    """One worker's batch: write the query file, push the request through
    the command FIFO, read the stats line (parity: reference
    ``process_query.py:82-111``)."""
    with Timer() as prep:
        qfile = os.path.join(nfs, f"query.{host}{wid}")
        write_query_file(qfile, part)
    req = Request(rconf, qfile, answer_fifo_path(nfs, host, wid), diff)
    row = fifo_transport.send_with_retry(host, req, command_fifo_path(wid),
                                         timeout=timeout)
    if not row.ok:
        log.error("worker %d on %s failed; marking row failed", wid, host)
    return row.as_list(t_prepare=prep.interval, t_partition=t_partition,
                       size=len(part))


def run_host(conf: ClusterConfig, args, queries, dc, diffs,
             t_partition: float = 0.0):
    rconf = runtime_config(args)
    groups = dc.group_queries(queries, active_worker=args.worker)
    # transport timeout is independent of the per-query search budget: a
    # short --ms-lim must not kill the ssh/FIFO round-trip itself; a long
    # budget extends the transport allowance proportionally
    timeout = max(fifo_transport.DEFAULT_TIMEOUT,
                  (get_time_ns(args) / 1e9) * 10)
    stats = []
    for diff in diffs:
        jobs = [(conf.workers[wid], wid, part) for wid, part in
                sorted(groups.items())]
        rows = fan_out(jobs, lambda j: send_queries(
            j[0], j[1], j[2], rconf, conf.nfs, diff,
            t_partition=t_partition, timeout=timeout))
        stats.append(rows)
    return stats


# ------------------------------------------------------------------- driver

def run(conf: ClusterConfig, args):
    """The campaign: returns ``(data, stats)`` with the reference's shapes
    (reference ``process_query.py:132-194``)."""
    scen = conf.scenfile or args.scenario
    with Timer() as t_read:
        queries = read_scen(scen)
    log.info("read %d queries from %s", len(queries), scen)

    with Timer() as t_workload:
        partmethod, partkey = effective_partition(conf, args)
        nodenum = xy_node_count(conf.xy_file)
        dc = DistributionController(partmethod, partkey, conf.maxworker,
                                    nodenum)
    diffs = list(conf.diffs) if conf.diffs else list(args.diffs)

    use_tpu = args.backend == "tpu" or (args.backend == "auto"
                                        and partmethod == "tpu")
    if use_tpu:
        from ..parallel.multihost import initialize_from_conf
        initialize_from_conf(conf)
    with Timer() as t_process:
        if use_tpu:
            stats = run_tpu(conf, args, queries, dc, diffs)
        else:
            stats = run_host(conf, args, queries, dc, diffs,
                             t_partition=t_workload.interval)

    data = {
        "num_queries": int(len(queries)),
        "num_partitions": conf.maxworker,
        "t_read": t_read.interval,
        "t_workload": t_workload.interval,
        "t_process": t_process.interval,
    }
    return data, stats


def output(data, stats, args) -> None:
    """Print, or write the artifact trio (reference
    ``process_query.py:196-239`` with the CSV bug fixed)."""
    if args.output is None:
        print(data)
        print(STATS_HEADER)
        for i, expe in enumerate(stats):
            for row in expe:
                print(i, row)
        return
    dirname = args.output
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "metrics.json"), "w") as f:
        json.dump(data, f)
    with open(os.path.join(dirname, "data.json"), "w") as f:
        json.dump(vars(args), f)
    with open(os.path.join(dirname, "parts.csv"), "w") as f:
        writer = csv.writer(f, quoting=csv.QUOTE_MINIMAL)
        writer.writerow(STATS_HEADER)
        writer.writerows([i, *row] for i, expe in enumerate(stats)
                         for row in expe)


def test(args):
    """Canned smoke campaign on the synthetic dataset (parity: reference
    ``process_query.py:241-256``; TPU-mode by default, sized to the local
    device count)."""
    import jax

    from ..data.synth import ensure_synth_dataset

    conf = test_config(n_workers=len(jax.devices()))
    ensure_synth_dataset(os.path.dirname(conf.xy_file) or "./data")
    data, stats = run(conf, args)
    output(data, stats, args)
    return data, stats


def main(argv=None) -> int:
    args = parse_args(argv, prog="process_query")
    set_verbosity(args.verbose)
    if args.debug:
        # deterministic repro mode (parity: reference offline.py:143-147)
        args.omp, args.verbose = 1, max(args.verbose, 2)
    import contextlib
    if args.profile:
        import jax
        trace = jax.profiler.trace(args.profile)
    else:
        trace = contextlib.nullcontext()
    with trace:
        if args.test:
            test(args)
            return 0
        conf = ClusterConfig.load(args.c)
        data, stats = run(conf, args)
        output(data, stats, args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
