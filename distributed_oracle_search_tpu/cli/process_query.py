"""Query campaign driver: the framework's ``process_query.py``.

Role parity with reference P4 (SURVEY.md §2.1, call stack §3.3): read the
scenario, partition queries by the worker owning each **target** node, run
one round per congestion diff, collect per-worker stats rows, and emit the
campaign artifacts.

Two backends behind one stats schema:

* ``partmethod=tpu`` — the north-star path: the CPD lives sharded on a
  device mesh; each diff round is answered by ONE sharded XLA call
  (``CPDOracle.query``) instead of N FIFO round-trips. Per-worker stats
  rows are recovered from the routed results, so downstream tooling sees
  the same ``parts.csv`` either way.
* host mode — the reference mechanism, modernized: query files to the
  shared dir, 2-line config through each worker's command FIFO, one CSV
  stats line back (``transport``), driven concurrently by a thread pool
  (reference ``process_query.py:180-185``), with explicit failure rows and
  retries instead of garbage rows (SURVEY.md §2.1 quirks).

Artifacts (``-o DIR``): ``metrics.json`` (phase timings), ``data.json``
(full arg dump), ``parts.csv`` (per-worker rows) — reference
``process_query.py:230-239``, with its multi-worker CSV crash fixed (the
reference's ``[[i] + row for i, row in stats]`` mis-unpacks, SURVEY.md §2.1).
"""

from __future__ import annotations

import csv
import dataclasses
import os
import sys

import numpy as np

from .args import get_time_ns, parse_args
from ..data.formats import read_diff, read_scen, xy_node_count
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..parallel.partition import DistributionController
from ..transport.fifo import answer_fifo_path, command_fifo_path, fan_out
from ..transport.wire import (
    Request, RuntimeConfig, STATS_HEADER, StatsRow, paths_file_for,
    read_paths_file, write_query_file,
)
from ..parallel import membership as fleet
from ..parallel.multihost import is_primary
from ..transport import fifo as fifo_transport
from ..transport import resilience
from ..transport import rpc as rpc_transport
from ..utils.atomicio import atomic_write_json, atomic_writer, sweep_stale_artifacts
from ..utils.config import ClusterConfig, test_config
from ..utils.env import env_cast, env_flag
from ..utils.log import get_logger, set_verbosity
from ..utils.timer import Timer

log = get_logger(__name__)

#: campaign exit codes — distinct so operators and CI can tell a fully
#: clean run from a degraded one (partial results + degraded.json) and
#: from a total failure (no batch succeeded). 1 and 2 are left to Python
#: tracebacks and argparse respectively.
EXIT_CLEAN = 0
EXIT_DEGRADED = 3
EXIT_FAILED = 4

# head-side phase metrics (obs/__init__.py maps these against the
# worker-side histograms and the wire stats fields)
H_PREPARE = obs_metrics.histogram(
    "head_prepare_seconds", "per-batch query-file write")
H_SEND = obs_metrics.histogram(
    "head_send_seconds",
    "FIFO round-trip: request push until the stats line lands")
H_PARTITION = obs_metrics.histogram(
    "head_partition_seconds", "campaign partition/workload setup")
H_SEARCH = obs_metrics.histogram(
    "head_search_seconds", "in-process (TPU-mode) per-round search call")
H_BATCHES = obs_metrics.counter("head_batches_total")
H_BATCH_FAIL = obs_metrics.counter(
    "head_batches_failed_total", "batches whose stats row came back FAIL")


def runtime_config(args) -> RuntimeConfig:
    """Per-batch engine knobs from CLI args (parity: reference
    ``process_query.py:149-160``)."""
    extract = bool(getattr(args, "extract", False))
    if extract and args.k_moves <= 0:
        raise SystemExit("--extract needs -k/--k-moves > 0")
    return RuntimeConfig(
        hscale=args.h_scale, fscale=args.f_scale, time=get_time_ns(args),
        itrs=args.itrs, k_moves=args.k_moves, threads=args.omp,
        verbose=args.verbose, debug=args.debug,
        thread_alloc=args.thread_alloc, no_cache=args.no_cache,
        extract=extract,
    )


def effective_partition(conf: ClusterConfig, args):
    """CLI ``--div/--mod/--alloc`` override the conf's partmethod (the
    reference's modus group, ``args.py:175-183``)."""
    if args.div is not None:
        return "div", args.div
    if args.mod is not None:
        return "mod", args.mod
    if args.alloc is not None:
        return "alloc", list(args.alloc)
    return conf.partmethod, conf.partkey


# ------------------------------------------------------------------ TPU path

class _StreamedServe:
    """Duck-typed stand-in for ``CPDOracle`` in :func:`run_tpu` when the
    resident ``[R, N]`` shard would not fit device memory: the campaign
    is served from the on-disk block files via
    :class:`~..models.streamed.StreamedCPDOracle` (chunks LRU-cached on
    device, RLE/4-bit packed uploads), with the ``-w`` filter applied
    host-side. Selected automatically when the per-device fm estimate
    exceeds ``DOS_FM_BUDGET_GB`` (default 8), or forced with
    ``DOS_SERVE_STREAMED=1``.

    Multi-controller runs SHARD the streamed campaign: process p serves
    only the workers with ``wid % process_count == p`` — its own
    device streams only those workers' rows, and the disjoint partial
    results merge with one allgather. This is the reference's
    concurrent-workers shape (one resident server per worker, driven
    concurrently — reference ``process_query.py:180-185``) applied to
    the streaming memory plan: W processes upload 1/W of the bytes each,
    in parallel, instead of every controller re-streaming the world.
    A missing index is likewise built process-sharded (each process
    writes its own workers' block files; a barrier precedes the
    manifest)."""

    def __init__(self, graph, dc, outdir: str, chunk: int):
        from ..models.cpd import build_worker_shard, write_index_manifest
        from ..models.streamed import StreamedCPDOracle
        from ..parallel.multihost import barrier, process_info

        self.pidx, self.pcount = process_info()
        #: bool [W] — workers THIS controller serves (all of them on a
        #: single-controller run)
        self.my_workers = (np.arange(dc.maxworker) % self.pcount
                           == self.pidx)
        if not os.path.exists(os.path.join(outdir, "index.json")):
            log.info("no index at %s; building %s block files "
                     "in-process", outdir,
                     "this process's workers'" if self.pcount > 1
                     else "per-worker")
            for wid in range(dc.maxworker):
                if self.my_workers[wid]:
                    build_worker_shard(graph, dc, wid, outdir,
                                       chunk=chunk)
            barrier("dos-streamed-build")
            if self.pidx == 0:
                write_index_manifest(outdir, dc)
            barrier("dos-streamed-manifest")
        self.dc = dc
        row_chunk = env_cast("DOS_STREAM_ROW_CHUNK", 4096, int)
        self.st = StreamedCPDOracle(graph, dc, outdir,
                                    row_chunk=row_chunk)

    def _split(self, queries, active_worker):
        owner = self.dc.worker_of(np.asarray(queries)[:, 1])
        active = self.my_workers[owner]
        if active_worker != -1:
            active = active & (owner == active_worker)
        return active, np.asarray(queries)[active]

    def _merge(self, *arrays):
        """Combine the processes' disjoint partial results (zeros/False
        outside each process's workers) into the full campaign answer on
        every controller. One allgather per array; no-op
        single-controller."""
        if self.pcount == 1:
            return arrays
        from ..parallel.multihost import gather_to_host

        out = []
        for a in arrays:
            if a.dtype == np.bool_:
                out.append(gather_to_host(a[None]).any(axis=0))
                continue
            # int64 payloads ride as int32 bit-pairs: jax without x64
            # would silently downcast an int64 allgather. Disjoint
            # support makes the bitwise trick exact — at every int32
            # position at most one process contributes nonzero bits, so
            # the int32 sum IS the original word pair, carry-free.
            bits = np.ascontiguousarray(a)[None].view(np.int32)
            g = gather_to_host(bits)             # [P, ..., 2*last]
            out.append(g.sum(axis=0, dtype=np.int32).view(a.dtype))
        return tuple(out)

    def query(self, queries, w_query=None, k_moves=-1, active_worker=-1,
              max_steps=0):
        active, part = self._split(queries, active_worker)
        c, p, f = self.st.query(part, w_query=w_query, k_moves=k_moves,
                                max_steps=max_steps)
        out = [np.zeros(len(queries), np.int64),
               np.zeros(len(queries), np.int64),
               np.zeros(len(queries), bool)]
        for o, got in zip(out, (c, p, f)):
            o[active] = got
        return self._merge(*out)

    def query_multi(self, queries, w_diffs, active_worker=-1,
                    max_steps=0):
        active, part = self._split(queries, active_worker)
        c, p, f = self.st.query_multi(part, w_diffs, max_steps=max_steps)
        out_c = np.zeros((len(w_diffs), len(queries)), np.int64)
        out_p = np.zeros(len(queries), np.int64)
        out_f = np.zeros(len(queries), bool)
        out_c[:, active] = c
        out_p[active] = p
        out_f[active] = f
        return self._merge(out_c, out_p, out_f)

    def query_paths(self, queries, k, active_worker=-1):
        """Path-prefix extraction from the streamed index: the fm rows
        each chunk uploads for the walk serve the extraction scan too
        (``StreamedCPDOracle.query_paths``), so ``--extract`` works
        under the streamed memory plan at no extra wire cost — and with
        the LRU warm from the cost rounds, usually zero uploads."""
        active, part = self._split(queries, active_worker)
        nodes, moves = self.st.query_paths(part, k=k)
        out_nodes = np.zeros((len(queries), k + 1), np.int64)
        out_moves = np.zeros(len(queries), np.int64)
        out_nodes[active] = nodes
        out_moves[active] = moves
        return self._merge(out_nodes, out_moves)


def _astar_heap_campaign(graph, queries, w_query, hscale, fscale,
                         deadline):
    """Per-query CPU heap A* over a batch (the fast index-free serving
    path; ``models.astar`` is the expansion-order-faithful oracle). The
    ns deadline truncates between queries; the first always runs."""
    import time as _time

    from ..models.astar import AstarStats, astar, min_cost_per_unit

    w = graph.w if w_query is None else w_query
    cpu = min_cost_per_unit(graph, w)
    st = AstarStats()
    cost = np.zeros(len(queries), np.int64)
    plen = np.zeros(len(queries), np.int64)
    fin = np.zeros(len(queries), bool)
    for i, (s, t) in enumerate(queries):
        if i and deadline is not None and _time.perf_counter() > deadline:
            break
        cost[i], plen[i], fin[i] = astar(
            graph, int(s), int(t), w, hscale=hscale, fscale=fscale,
            cpu=cpu, stats=st)
    return cost, plen, fin, dict(
        n_expanded=st.n_expanded, n_inserted=st.n_inserted,
        n_touched=st.n_touched, n_updated=st.n_updated,
        n_surplus=st.n_surplus)


def run_tpu(conf: ClusterConfig, args, queries, dc, diffs):
    """All diff rounds in-process on the mesh; per-worker rows recovered
    from the routed results.

    Per-worker timing semantics: one fused sharded XLA call answers the
    whole round, so a per-worker wall clock does not exist. Each row's
    ``t_astar``/``t_search`` (and ``t_receive``/``t_prepare``) carry the
    worker's SHARE of the round interval, apportioned by walked moves
    (by batch size when no moves) — rows of a round sum to the measured
    round time, so downstream tooling that aggregates per-worker columns
    gets campaign-true totals (tests pin this).
    """
    import jax

    from ..data.graph import Graph
    from ..models.cpd import CPDOracle
    from ..parallel.mesh import mesh_from_config

    alg = getattr(args, "alg", "table-search")
    if alg == "ch":
        raise SystemExit(
            "--alg ch is served by the native engine only "
            "(--backend host with make_fifos --engine native); the "
            "hierarchy is a pointer-chasing CPU structure with no "
            "device analog here")

    graph = Graph.from_xy(conf.xy_file)
    if jax.process_count() == 1:
        # artifact-plane analog of run_host's stale-FIFO sweep: tmp
        # debris / quarantined blocks from killed builds go before the
        # build-if-missing paths below can trip on them. Skipped
        # multi-controller — a peer process may have an atomic write in
        # flight in the shared index dir.
        sweep_stale_artifacts(conf.outdir)
    use_astar = alg == "astar"
    if use_astar:
        # A* searches the graph directly — no CPD index involved.
        # Default engine: the CPU heap oracle — the batched device
        # kernel is the index-free PARITY path, not the fast one (its
        # dense lock-step sweeps measured ~160x slower than the heap on
        # the bench graph, BENCH_r04), and a serving CLI must not route
        # users to the slowest backend in the building.
        # DOS_ASTAR_DEVICE=1 opts into the device kernel explicitly.
        from ..ops.batched_astar import astar_batch_np

        astar_device = env_flag("DOS_ASTAR_DEVICE", False)
        log.info(
            "--alg astar served by the %s", "batched DEVICE kernel "
            "(DOS_ASTAR_DEVICE=1)" if astar_device else
            "CPU heap engine (the fast A* backend; set "
            "DOS_ASTAR_DEVICE=1 for the batched device kernel)")
        astar_ctx: dict = {}
        oracle = None
    else:
        # memory plan: resident sharded oracle when the per-device fm
        # shard fits, else serve streamed from the on-disk index (the
        # regime where one chip's N^2/W outgrows HBM — README "Serving
        # modes"). DOS_SERVE_STREAMED=1 forces; DOS_FM_BUDGET_GB
        # (default 8) is the per-device residency budget.
        fm_gb = env_cast("DOS_FM_BUDGET_GB", 8.0, float)
        est_shard = dc.max_owned * graph.n            # int8 fm bytes
        forced = env_flag("DOS_SERVE_STREAMED", False)
        if forced or est_shard > fm_gb * 1e9:
            log.info(
                "serving streamed%s: per-device fm shard %.2f GB vs "
                "budget %.1f GB (DOS_FM_BUDGET_GB)",
                " (forced by DOS_SERVE_STREAMED=1)" if forced else "",
                est_shard / 1e9, fm_gb)
            oracle = _StreamedServe(graph, dc, conf.outdir, args.chunk)
        else:
            mesh = mesh_from_config(conf)
            oracle = CPDOracle(graph, dc, mesh=mesh)
            try:
                oracle.load(conf.outdir)
            except FileNotFoundError:
                log.info("no index at %s; building in-process",
                         conf.outdir)
                oracle.build(chunk=args.chunk)
                oracle.save(conf.outdir)

    owner = dc.worker_of(queries[:, 1])
    time_ns = get_time_ns(args)
    stats = []
    paths = None
    # fused multi-diff: table-search trajectories are diff-independent
    # (moves follow the FREE-FLOW first-move table), so a multi-diff
    # campaign — the reference's one-round-per-diff loop — walks ONCE
    # and accumulates every round's costs (models.cpd.query_multi).
    # Outputs are bit-identical to sequential rounds; each round's
    # timers carry an equal share of the fused interval (rows still sum
    # to the measured campaign time). k_moves budgets fall back to
    # sequential rounds (the fused kernel serves the unlimited default).
    fused = None
    if not use_astar and len(diffs) > 1 and args.k_moves < 0:
        with Timer() as fprep, obs_trace.span("head.prepare", fused=True):
            w_list = [None if d == "-"
                      else graph.weights_with_diff(read_diff(d))
                      for d in diffs]
        with Timer() as fsearch, obs_trace.span("head.search", fused=True,
                                                rounds=len(diffs)):
            f_cost, f_plen, f_fin = oracle.query_multi(
                queries, w_list, active_worker=args.worker)
        # histogram stays per-round like the sequential path (and like
        # the stats rows): one equal share per fused round
        for _ in diffs:
            H_SEARCH.observe(fsearch.interval / len(diffs))
        fused = (f_cost, f_plen, f_fin,
                 fprep.interval / len(diffs),
                 fsearch.interval / len(diffs))
        log.info("fused %d diff rounds in one walk (%.3fs)",
                 len(diffs), fsearch.interval)
    for di, diff in enumerate(diffs):
        counters = {}
        active = (np.ones(len(queries), bool) if args.worker == -1
                  else owner == args.worker)
        if fused is not None:
            cost, plen, fin = fused[0][di], fused[1], fused[2]
            prep_iv, search_iv = fused[3], fused[4]
        else:
            with Timer() as prep, obs_trace.span("head.prepare",
                                                 diff=diff):
                w_query = (None if diff == "-"
                           else graph.weights_with_diff(read_diff(diff)))
            if use_astar:
                import time as _time

                deadline = (_time.perf_counter() + time_ns / 1e9
                            if time_ns else None)
                with Timer() as search, obs_trace.span("head.search",
                                                       alg="astar"):
                    cost = np.zeros(len(queries), np.int64)
                    plen = np.zeros(len(queries), np.int64)
                    fin = np.zeros(len(queries), bool)
                    if astar_device:
                        c, p, f, counters = astar_batch_np(
                            graph, queries[active], w=w_query,
                            hscale=args.h_scale, fscale=args.f_scale,
                            deadline=deadline, ctx=astar_ctx,
                            w_key=diff if not args.no_cache else None)
                    else:
                        c, p, f, counters = _astar_heap_campaign(
                            graph, queries[active], w_query,
                            args.h_scale, args.f_scale, deadline)
                    cost[active], plen[active], fin[active] = c, p, f
            else:
                with Timer() as search, obs_trace.span(
                        "head.search", alg="table-search", diff=diff):
                    cost, plen, fin = oracle.query(
                        queries, w_query=w_query, k_moves=args.k_moves,
                        active_worker=args.worker)
            prep_iv, search_iv = prep.interval, search.interval
            H_SEARCH.observe(search_iv)
        total_moves = int(plen[active].sum())
        total_size = int(active.sum())
        rows = []
        for wid in range(dc.maxworker):
            if args.worker != -1 and wid != args.worker:
                continue
            mask = owner == wid
            size = int(mask.sum())
            if size == 0:
                continue
            moves = int(plen[mask].sum())
            share = (moves / total_moves if total_moves
                     else size / max(total_size, 1))
            # A* emits the full priority-queue telemetry, apportioned by
            # the same share rule as the timers (one fused batch has no
            # per-worker counters); table-search keeps its walk counters
            row = StatsRow(
                n_expanded=(int(counters.get("n_expanded", 0) * share)
                            if use_astar else moves),
                n_inserted=int(counters.get("n_inserted", 0) * share),
                n_touched=(int(counters.get("n_touched", 0) * share)
                           if use_astar else size),
                n_updated=int(counters.get("n_updated", 0) * share),
                n_surplus=int(counters.get("n_surplus", 0) * share),
                plen=moves,
                finished=int(fin[mask].sum()),
                t_receive=prep_iv * share,
                t_astar=search_iv * share,
                t_search=search_iv * share,
            )
            rows.append(row.as_list(t_prepare=prep_iv * share,
                                    t_partition=0.0, size=size))
        stats.append(rows)
    if getattr(args, "extract", False) and args.k_moves > 0:
        if use_astar:
            # reference semantics: "K-moves are only available with
            # extractions while hScale only influences A*" (args.py:28)
            log.warning("--extract is a table-search feature; ignored "
                        "for --alg astar")
        else:
            # moves always follow the FREE-FLOW first-move table
            # (reference semantics), so path prefixes are diff-invariant:
            # extract once
            nodes, moves = oracle.query_paths(queries, k=args.k_moves,
                                              active_worker=args.worker)
            paths = np.concatenate(
                [queries, moves[:, None], nodes], axis=1)
    return stats, paths


# ----------------------------------------------------------------- host path

#: DOS_TRANSPORT=auto lanes that proved to have no RPC listener —
#: sticky for the process (the serving AutoDispatcher contract): a
#: pure-FIFO fleet pays ONE failed dial + ONE warning per lane, not a
#: connect attempt per batch. GIL-atomic set mutations; a worker that
#: GAINS a listener mid-campaign is picked up on the next process.
_RPC_FALLBACK_LANES: set = set()


def send_queries(host: str, wid: int, part: np.ndarray, rconf: RuntimeConfig,
                 nfs: str, diff: str, t_partition: float = 0.0,
                 timeout: float | None = fifo_transport.DEFAULT_TIMEOUT,
                 trace_id: str = "", round_idx: int = 0,
                 policy: fifo_transport.RetryPolicy | None = None,
                 registry: resilience.BreakerRegistry | None = None,
                 candidates=None):
    """One shard's batch: write the query file, push the request through
    the command FIFO, read the stats line (parity: reference
    ``process_query.py:82-111``). A non-empty ``trace_id`` stamps the
    batch's head-side spans AND rides the wire so the worker captures its
    half under the same id.

    ``candidates``: the shard's replica chain as ``(host, wid)`` pairs
    in failover order (default: just the primary — the R=1 behavior).
    A candidate whose circuit breaker is OPEN is skipped without a
    send, and when ``send_with_retry`` exhausts on one candidate the
    batch re-routes to the next (``failover_total``) — only a batch
    every replica refused or failed is booked degraded.

    Returns ``(row_list, failure, served)`` where ``failure`` is None on
    success or a dict describing the failed batch for the
    ``degraded.json`` manifest, and ``served`` is the ``(host, wid)``
    that answered (None on failure) — the extraction/trace collectors
    read sidecars next to the query file the SERVING worker actually
    saw."""
    prep_total = [0.0]
    last_qfile = [""]

    def _attempt(key):
        c_host, c_wid = key
        # a re-routed batch must NOT share another batch's file/FIFO
        # names: shard w's failed-over batch and the serving worker's
        # OWN batch run concurrently in the same round, and a shared
        # `query.<host><wid>` / `answer.<host><wid>` pair would tear.
        # Bare names are reserved for the c_wid == wid case (worker id
        # doubles as shard id — the legacy invariant, byte-for-byte);
        # any other (shard, worker) pairing suffixes the SHARD id, so
        # two shards owned by one worker after an elastic epoch can
        # never collide on the primary name. The suffix always carries
        # `.e<epoch>` (epoch 0 included — the first migration window
        # opens BEFORE the first bump): a dual-read window's files are
        # attributable to their table version, and an aborted window's
        # debris is collectible by the campaign-start epoch sweep
        # (transport.fifo.clean_stale_epoch_files)
        epoch = getattr(rconf, "epoch", 0)
        suffix = "" if c_wid == wid else f".s{wid}.e{epoch}"
        qfile = os.path.join(nfs, f"query.{c_host}{c_wid}{suffix}")
        rc = (dataclasses.replace(rconf, trace_id=trace_id)
              if trace_id else rconf)
        # streaming lane (DOS_TRANSPORT=rpc/auto): the batch rides a
        # persistent socket as a raw int64 frame segment — no query
        # file, no transfer script, no FIFO rendezvous. Paths/trace
        # payloads still materialize as the legacy sidecars NEXT TO
        # the (never-written) query-file name, so the extraction and
        # trace collectors read them unchanged. `auto` falls through
        # to the FIFO wire when this worker has no listener — STICKY
        # per (host, wid) like the serving AutoDispatcher, so a
        # pure-FIFO fleet pays one failed dial per lane, not per batch.
        mode = rpc_transport.resolve_transport()
        if mode in ("rpc", "auto") and (
                mode == "rpc"
                or (c_host, c_wid) not in _RPC_FALLBACK_LANES):
            try:
                with Timer() as send, obs_trace.span(
                        "head.send", wid=c_wid, shard=wid, diff=diff,
                        trace_id=trace_id):
                    row = rpc_transport.send_batch_with_retry(
                        c_host, c_wid, part, rc, diff, timeout=timeout,
                        policy=policy, sidecar_base=qfile)
                H_SEND.observe(send.interval)
                last_qfile[0] = qfile
                return row
            except rpc_transport.RpcUnavailable as e:
                if mode == "rpc":
                    log.error("worker %d on %s has no rpc listener "
                              "(DOS_TRANSPORT=rpc): %s", c_wid, c_host,
                              e)
                    return StatsRow.failed()
                _RPC_FALLBACK_LANES.add((c_host, c_wid))
                log.warning("worker %d on %s has no rpc listener; "
                            "lane falls back to the FIFO wire",
                            c_wid, c_host)
        with Timer() as prep, obs_trace.span("head.prepare", wid=c_wid,
                                             shard=wid,
                                             trace_id=trace_id):
            write_query_file(qfile, part)
        H_PREPARE.observe(prep.interval)
        prep_total[0] += prep.interval
        last_qfile[0] = qfile
        req = Request(rc, qfile,
                      answer_fifo_path(nfs, c_host, c_wid) + suffix,
                      diff)
        with Timer() as send, obs_trace.span("head.send", wid=c_wid,
                                             shard=wid, diff=diff,
                                             trace_id=trace_id):
            row = fifo_transport.send_with_retry(
                c_host, req, command_fifo_path(c_wid), timeout=timeout,
                policy=policy, wid=c_wid)
        H_SEND.observe(send.interval)
        return row

    candidates = list(candidates) if candidates else [(host, wid)]
    row, served, reasons = resilience.send_failover(
        candidates, _attempt, registry=registry)
    H_BATCHES.inc()
    if row is None:
        row = StatsRow.failed()
    if served is not None:
        if served != candidates[0]:
            log.warning("shard %d batch failed over %s -> worker %d on "
                        "%s", wid, [r for r in reasons], served[1],
                        served[0])
        return (row.as_list(t_prepare=prep_total[0],
                            t_partition=t_partition, size=len(part)),
                None, (served[0], served[1], last_qfile[0]))
    H_BATCH_FAIL.inc()
    # degraded reason keeps the R=1 vocabulary (chaos tests pin it):
    # "circuit-open" when no candidate was even attempted, else
    # "send-failed"; the per-candidate trail rides along for operators
    reason = ("circuit-open"
              if all(r == "circuit-open" for _, r in reasons)
              else "send-failed")
    log.error("shard %d batch failed on every replica: %s", wid,
              [(k[1], r) for k, r in reasons])
    failure = {"wid": wid, "host": host, "round": round_idx,
               "diff": diff, "size": int(len(part)), "reason": reason}
    if len(candidates) > 1:
        failure["replicas_tried"] = [
            {"host": k[0], "wid": k[1], "reason": r}
            for k, r in reasons]
    return (row.as_list(t_prepare=prep_total[0],
                        t_partition=t_partition, size=len(part)),
            failure, None)


def send_timeout_s(args) -> float:
    """Transport timeout: independent of the per-query search budget (a
    short ``--ms-lim`` must not kill the ssh/FIFO round-trip itself; a
    long budget extends the transport allowance proportionally).
    ``DOS_SEND_TIMEOUT_S`` overrides outright — chaos tests and operators
    with known-fast batches use it to keep dead-worker detection far
    below the 10-minute default."""
    override = env_cast("DOS_SEND_TIMEOUT_S", None, float)
    if override is not None:
        return override
    return max(fifo_transport.DEFAULT_TIMEOUT,
               (get_time_ns(args) / 1e9) * 10)


def run_host(conf: ClusterConfig, args, queries, dc, diffs,
             t_partition: float = 0.0, mstate=None):
    rconf = runtime_config(args)
    groups = dc.group_queries(queries, active_worker=args.worker)
    timeout = send_timeout_s(args)
    transport_mode = rpc_transport.resolve_transport()
    if transport_mode != "fifo":
        log.info("campaign data plane: DOS_TRANSPORT=%s (persistent "
                 "sockets%s)", transport_mode,
                 "; per-lane FIFO fallback"
                 if transport_mode == "auto" else "")
    # fault-tolerance plumbing: stale FIFOs from crashed runs are swept
    # before the first batch (a killed transfer script never reaches its
    # `rm -f`), stale build artifacts (*.tmp debris, quarantined blocks)
    # and epoch-suffixed wire files from an aborted migration window go
    # with them, retries follow the env-tuned backoff policy, and
    # each worker gets a circuit breaker whose background probes ping
    # through the same command FIFO the batches use
    fifo_transport.clean_stale_answer_fifos(conf.nfs)
    fifo_transport.clean_stale_epoch_files(conf.nfs)
    sweep_stale_artifacts(conf.outdir)
    policy = fifo_transport.RetryPolicy.from_env()
    registry = resilience.BreakerRegistry(
        probe_fn=lambda key: fifo_transport.probe(
            key[0], key[1], command_fifo=command_fifo_path(key[1]),
            nfs=conf.nfs))
    # per-batch trace ids: campaign id + worker + round, stamped on the
    # head spans and propagated over the wire (obs.trace wire extension)
    tracing = obs_trace.enabled()
    base_tid = (obs_trace.current_trace_id()
                or obs_trace.new_trace_id()) if tracing else ""
    stats = []
    paths = None
    failures = []
    try:
        stats, paths, failures = _run_host_rounds(
            conf, args, dc, diffs, groups, rconf, t_partition, timeout,
            tracing, base_tid, policy, registry, mstate=mstate)
    finally:
        registry.shutdown()
        # persistent RPC connections live for the whole campaign; drop
        # them with it (harmless no-op on the pure-FIFO lane)
        rpc_transport.close_clients()
    if failures:
        log.error("campaign degraded: %d failed batch(es) across "
                  "workers %s", len(failures),
                  sorted({f["wid"] for f in failures}))
    return stats, paths, failures


def _round_membership(conf, dc, last=None):
    """One round's live routing view: the durable membership state (or
    None on a static fleet), the matching controller, the host roster,
    and the round's epoch-stamped knobs. Re-read EVERY round so a
    reconfiguration committed mid-campaign flips the very next round's
    routing — this is what makes a campaign survive a live join/leave
    without draining.

    ``last`` is the previous round's (state, controller, roster)
    triple: a read that fails — or a state file that VANISHES after an
    elastic view was already in effect — degrades to that last-good
    view, never to a mix. The table and the roster must come from the
    same state: ``dc`` may already carry a committed owner table whose
    joined worker ids are past the static conf roster, and pairing it
    with ``conf.workers`` would wrap those ids onto the wrong hosts."""
    try:
        mview = fleet.load_state(conf.outdir)
    except ValueError as e:
        if last is not None:
            log.error("membership state unreadable (%s); keeping the "
                      "previous round's table", e)
            return last
        log.error("membership state unreadable (%s); keeping the "
                  "current table", e)
        mview = None
    if (mview is None and last is not None and last[0] is not None):
        log.error("membership state vanished; keeping the previous "
                  "round's table")
        return last
    if last is not None and last[0] is not None and mview is not None:
        if mview.epoch < last[0].epoch:
            # epochs are monotone: a lagging read (NFS cache, a
            # restored stale file) must not roll routing back to a
            # drained owner — the refresh()/worker-gate rule
            log.error("membership state read epoch %d behind round's "
                      "%d; keeping the previous round's table",
                      mview.epoch, last[0].epoch)
            return last
        if mview.to_dict() == last[0].to_dict():
            # unchanged: reuse the controller instead of re-running
            # the O(N) node assignment every round
            return last
    try:
        dc_r = fleet.apply_state(dc, mview) if mview is not None else dc
    except ValueError as e:
        # an owners table that does not fit this partition (conf
        # mismatch, hand edit) degrades instead of crashing the round
        if last is not None:
            log.error("membership state does not apply (%s); keeping "
                      "the previous round's table", e)
            return last
        log.error("membership state does not apply (%s); keeping the "
                  "static table", e)
        mview, dc_r = None, dc
    hosts = (list(mview.workers) if mview is not None and mview.workers
             else list(conf.workers))
    return mview, dc_r, hosts


def _run_host_rounds(conf, args, dc, diffs, groups, rconf, t_partition,
                     timeout, tracing, base_tid, policy, registry,
                     mstate=None):
    stats = []
    paths = None
    failures = []
    # last-good (state, table, roster) triple: seeded from the startup
    # view so even a ROUND-0 read failure under an elastic table keeps
    # the roster that names the joined workers' hosts
    last = None
    if mstate is not None:
        last = (mstate, dc,
                list(mstate.workers) if mstate.workers
                else list(conf.workers))
    for di, diff in enumerate(diffs):
        mview, dc_r, hosts = _round_membership(conf, dc, last=last)
        last = (mview, dc_r, hosts)
        rconf_r = (dataclasses.replace(rconf, epoch=dc_r.epoch)
                   if dc_r.epoch else rconf)

        def _host_of(c: int) -> str:
            return hosts[c] if c < len(hosts) else hosts[c % len(hosts)]

        jobs = [(_host_of(dc_r.owner_of(wid)), wid, part)
                for wid, part in sorted(groups.items())]
        results = fan_out(jobs, lambda j: send_queries(
            j[0], j[1], j[2], rconf_r, conf.nfs, diff,
            t_partition=t_partition, timeout=timeout,
            trace_id=f"{base_tid}/w{j[1]}.d{di}" if tracing else "",
            round_idx=di, policy=policy, registry=registry,
            candidates=[(_host_of(c), c)
                        for c in fleet.route_candidates(mview, dc_r,
                                                        j[1])]))
        rows = [row for row, _failure, _served in results]
        failures.extend(f for _row, f, _served in results
                        if f is not None)
        stats.append(rows)
        served_by = {wid: served for (_h, wid, _p), (_r, _f, served)
                     in zip(jobs, results) if served is not None}
        if tracing:
            # merge the workers' span sidecars for this round (absent
            # when a worker predates the wire extension — skip quietly;
            # sidecars sit next to the query file of the worker that
            # actually SERVED the batch, which failover may have moved)
            for host, wid, part in jobs:
                _h, _w, s_qfile = served_by.get(
                    wid, (host, wid,
                          os.path.join(conf.nfs, f"query.{host}{wid}")))
                sidecar = obs_trace.trace_sidecar_for(s_qfile)
                try:
                    obs_trace.ingest(obs_trace.read_events(sidecar))
                    os.remove(sidecar)
                except (OSError, ValueError):
                    log.debug("no trace sidecar from worker %d", wid)
        if rconf.extract and paths is None:
            # prefixes follow free-flow moves -> diff-invariant; collect
            # each worker's .paths file from the first round only
            parts = []
            for host, wid, part in jobs:
                _h, _w, s_qfile = served_by.get(
                    wid, (host, wid,
                          os.path.join(conf.nfs, f"query.{host}{wid}")))
                pfile = paths_file_for(s_qfile)
                try:
                    nodes, moves = read_paths_file(pfile)
                except (OSError, ValueError) as e:
                    log.error("no paths from worker %d (%s); skipping", wid,
                              e)
                    continue
                parts.append(np.concatenate(
                    [part, moves[:, None], nodes], axis=1))
            if parts:
                paths = np.concatenate(parts, axis=0)
    return stats, paths, failures


# ------------------------------------------------------------------- driver

def run(conf: ClusterConfig, args):
    """The campaign: returns ``(data, stats)`` with the reference's shapes
    (reference ``process_query.py:132-194``)."""
    if getattr(args, "order", None):
        # reordering relabels node ids EVERYWHERE (graph, index, scen,
        # diffs); doing it per-campaign would desync from the on-disk
        # index. The supported flow reorders the dataset once, up front.
        raise SystemExit(
            "--order is applied at dataset-preparation time, not per "
            "campaign: run `python -m distributed_oracle_search_tpu."
            f"cli.reorder --input {conf.xy_file} --order {args.order} "
            "-o <out.xy> --scen <in> <out>` once and point the conf at "
            "the reordered files (build + serve then agree by "
            "construction).")
    scen = conf.scenfile or args.scenario
    with Timer() as t_read, obs_trace.span("head.read", scen=scen):
        queries = read_scen(scen)
    log.info("read %d queries from %s", len(queries), scen)

    with Timer() as t_workload, obs_trace.span("head.partition"):
        partmethod, partkey = effective_partition(conf, args)
        nodenum = xy_node_count(conf.xy_file)
        use_tpu = args.backend == "tpu" or (args.backend == "auto"
                                            and partmethod == "tpu")
        # replication is a host-wire concept (replica block sets on
        # distinct workers + failover over the FIFO wire); the
        # in-process CAMPAIGN mesh routes every query to its primary
        # owner and its build-if-missing path saves a primary-only
        # index, so TPU campaigns pin R=1. The TPU-backed path that
        # DOES serve replicas is the serving layer (EngineDispatcher /
        # worker server): there replica rank r pins to worker-mesh
        # lane r % L (DOS_MESH_DEVICES, worker.engine replica-lane
        # placement), giving breaker/hedge/failover a real second
        # device on one host.
        replication = 1 if use_tpu else conf.effective_replication()
        if use_tpu and conf.effective_replication() > 1:
            log.info("replication=%d ignored on the TPU campaign "
                     "backend (queries route to primary owners only; "
                     "replica LANES apply to the serving layer — see "
                     "README 'Worker mesh')",
                     conf.effective_replication())
        dc = DistributionController(partmethod, partkey, conf.maxworker,
                                    nodenum, replication=replication)
        # elastic membership (host wire only, like replication: the
        # in-process mesh has no per-worker placement to reassign): a
        # committed epoch's owner table overrides the conf's static
        # identity, and each round re-reads it so a reconfiguration
        # committed mid-campaign flips the next round's routing
        if not use_tpu:
            mstate = fleet.load_state(conf.outdir)
            if mstate is not None:
                dc = fleet.apply_state(dc, mstate)
                log.info("membership epoch %d in effect (%d worker(s) "
                         "in roster)", dc.epoch, len(mstate.workers))
        elif fleet.current_epoch(conf.outdir):
            log.info("membership state ignored on the TPU backend "
                     "(in-process mesh: placement is the mesh itself)")
    H_PARTITION.observe(t_workload.interval)
    diffs = list(conf.diffs) if conf.diffs else list(args.diffs)
    if use_tpu:
        from ..parallel.multihost import initialize_from_conf
        initialize_from_conf(conf)
    with Timer() as t_process:
        if use_tpu:
            stats, paths = run_tpu(conf, args, queries, dc, diffs)
            failures = []   # in-process rounds have no per-worker wire
        else:
            stats, paths, failures = run_host(
                conf, args, queries, dc, diffs,
                t_partition=t_workload.interval, mstate=mstate)

    data = {
        "num_queries": int(len(queries)),
        "num_partitions": conf.maxworker,
        "t_read": t_read.interval,
        "t_workload": t_workload.interval,
        "t_process": t_process.interval,
        "failed_batches": failures,
    }
    return data, stats, paths


def campaign_exit_code(data, stats) -> int:
    """Clean / degraded / failed from the collected failure records."""
    failures = data.get("failed_batches", [])
    if not failures:
        return EXIT_CLEAN
    total = sum(len(expe) for expe in stats)
    return EXIT_FAILED if len(failures) >= total else EXIT_DEGRADED


def write_degraded_manifest(dirname: str, data, stats) -> str:
    """``degraded.json`` next to the other campaign artifacts: which
    batches failed, on which workers, and why — the machine-readable
    companion of the non-zero exit code."""
    failures = data.get("failed_batches", [])
    manifest = {
        "exit_code": campaign_exit_code(data, stats),
        "total_batches": sum(len(expe) for expe in stats),
        "failed_count": len(failures),
        "failed_workers": sorted({f["wid"] for f in failures}),
        "failed_batches": failures,
    }
    path = os.path.join(dirname, "degraded.json")
    atomic_write_json(path, manifest)
    return path


def output(data, stats, args, paths=None) -> None:
    """Print, or write the artifact trio (reference
    ``process_query.py:196-239`` with the CSV bug fixed), plus
    ``paths.csv`` when ``--extract`` collected prefixes: one row per
    query, ``s, t, moves, n0..nk`` (free-flow, diff-invariant)."""
    if args.output is None:
        print(data)
        print(STATS_HEADER)
        for i, expe in enumerate(stats):
            for row in expe:
                print(i, row)
        if paths is not None:
            k = paths.shape[1] - 4
            print(["s", "t", "moves"] + [f"n{j}" for j in range(k + 1)])
            for row in paths[:10]:
                print(list(row))
            if len(paths) > 10:
                print(f"... {len(paths)} path rows (use -o DIR for all)")
        return
    dirname = args.output
    os.makedirs(dirname, exist_ok=True)
    atomic_write_json(os.path.join(dirname, "metrics.json"), data)
    atomic_write_json(os.path.join(dirname, "data.json"), vars(args))
    with atomic_writer(os.path.join(dirname, "parts.csv")) as f:
        writer = csv.writer(f, quoting=csv.QUOTE_MINIMAL)
        writer.writerow(STATS_HEADER)
        writer.writerows([i, *row] for i, expe in enumerate(stats)
                         for row in expe)
    # obs snapshot next to the stats CSV: the campaign's counters and
    # per-phase histograms (obs.metrics), complementing the coarse
    # phase timings in metrics.json
    obs_metrics.REGISTRY.dump_json(
        os.path.join(dirname, "obs_metrics.json"))
    if data.get("failed_batches"):
        path = write_degraded_manifest(dirname, data, stats)
        log.error("degraded campaign: manifest written to %s", path)
    if paths is not None:
        k = paths.shape[1] - 4
        with atomic_writer(os.path.join(dirname, "paths.csv")) as f:
            writer = csv.writer(f, quoting=csv.QUOTE_MINIMAL)
            writer.writerow(["s", "t", "moves"]
                            + [f"n{j}" for j in range(k + 1)])
            writer.writerows(paths.tolist())


def test(args):
    """Canned smoke campaign on the synthetic dataset (parity: reference
    ``process_query.py:241-256``; TPU-mode by default, sized to the local
    device count)."""
    import jax

    from ..data.synth import ensure_synth_dataset

    conf = test_config(n_workers=len(jax.devices()))
    ensure_synth_dataset(os.path.dirname(conf.xy_file) or "./data")
    data, stats, paths = run(conf, args)
    if is_primary():
        output(data, stats, args, paths)
    return data, stats


def _finish_obs(args) -> None:
    """Write the ``--trace`` / ``--metrics-dump`` artifacts (primary
    process only — every controller ran the identical campaign)."""
    if not is_primary():
        return
    trace_path = getattr(args, "trace", "")
    if trace_path:
        obs_trace.write_trace(trace_path)
        log.info("wrote %d trace events to %s (open in Perfetto)",
                 len(obs_trace.events()), trace_path)
    dump = getattr(args, "metrics_dump", "")
    if dump:
        obs_metrics.REGISTRY.dump_json(dump)
        log.info("wrote metrics snapshot to %s", dump)


def main(argv=None) -> int:
    args = parse_args(argv, prog="process_query")
    set_verbosity(args.verbose)
    if args.debug:
        # deterministic repro mode (parity: reference offline.py:143-147)
        args.omp, args.verbose = 1, max(args.verbose, 2)
    if getattr(args, "trace", ""):
        obs_trace.enable()
        obs_trace.set_trace_id(obs_trace.new_trace_id())
    # live scrape endpoints for the campaign's lifetime (opt-in): a
    # long road-scale campaign is observable while it runs, not only
    # from its exit artifacts
    from ..obs.http import start_obs_server
    obs_srv = start_obs_server(getattr(args, "obs_port", None))
    import contextlib
    if args.profile:
        import jax
        trace = jax.profiler.trace(args.profile)
    else:
        trace = contextlib.nullcontext()
    try:
        with trace:
            if args.test:
                data, stats = test(args)
                _finish_obs(args)
                return campaign_exit_code(data, stats)
            conf = ClusterConfig.load(args.c)
            data, stats, paths = run(conf, args)
            # multi-controller: every process runs the identical
            # campaign; only process 0 writes/prints the shared
            # artifacts
            if is_primary():
                output(data, stats, args, paths)
            _finish_obs(args)
    finally:
        if obs_srv is not None:
            obs_srv.close()
    code = campaign_exit_code(data, stats)
    if code != EXIT_CLEAN:
        log.error("campaign finished %s (exit %d): %d/%d batches failed%s",
                  "DEGRADED" if code == EXIT_DEGRADED else "FAILED",
                  code, len(data.get("failed_batches", [])),
                  sum(len(expe) for expe in stats),
                  f"; manifest at {os.path.join(args.output, 'degraded.json')}"
                  if args.output else "")
    return code


if __name__ == "__main__":
    sys.exit(main())
