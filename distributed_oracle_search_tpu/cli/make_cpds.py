"""Distributed CPD precompute launcher: the framework's ``make_cpds.py``.

Role parity with reference P2 (SURVEY.md §2.1): read the cluster conf, then
for each worker start the per-worker CPD build.

* ``partmethod=tpu`` (the north-star path): no ssh at all — one in-process
  sharded build over the device mesh (every mesh shard builds its rows in
  parallel, SURVEY.md §2.3 "build parallelism"), then the index is saved to
  ``outdir`` with its manifest.
* host partmethods (``div``/``mod``/``alloc``): launch one
  ``worker.build`` process per worker — ssh + detached tmux for remote
  hosts (the reference's mechanism, ``make_cpds.py:21``), tracked local
  subprocesses for localhost. Unlike the reference's fire-and-forget
  (SURVEY.md §3.1 "no completion signal"), local builds are awaited and the
  index manifest is written when all shards are present.

``-t`` runs the canned smoke config; ``-w N`` restricts to one worker
(reference ``make_cpds.py:27-41,58-62``). ``--verify`` runs a
check-only integrity pass over the conf's index instead of building
(exit 0/3/4 clean/degraded/corrupt); ``--scrub`` repeats that pass on
a cadence (``--scrub-interval``/``--scrub-passes``) and exits with the
worst code seen — the at-rest counterpart of the serve-side resident
scrubber; ``--no-resume`` disables the
ledger-based crash-resume (on by default). ``--delta-from OLD --diff
FUSED`` runs a DELTA rebuild: only rows the fused diff's changed edges
can affect are recomputed, untouched blocks byte-copy, and the result
lands as an epoch-tagged index (``OLD/epoch-e<N>``) the serve path can
promote without restart.
"""

from __future__ import annotations

import json
import os
import sys

from .args import parse_args
from ..transport.launch import launch, session_name
from ..utils.atomicio import sweep_stale_artifacts
from ..utils.config import ClusterConfig, test_config
from ..utils.log import get_logger, set_verbosity

log = get_logger(__name__)


def worker_build_cmd(wid: int, conf: ClusterConfig, chunk: int = 0,
                     engine: str = "python",
                     resume: bool = True,
                     codec: str | None = None) -> str:
    """The shell command a host-mode worker runs (our ``make_cpd_auto``)."""
    partkey = (" ".join(str(b) for b in conf.partkey)
               if isinstance(conf.partkey, (list, tuple))
               else str(conf.partkey))
    if engine == "native":
        from ..utils.nativebin import require_binary
        if chunk:
            log.warning("--chunk is a JAX-builder staging knob; the native "
                        "builder works block-by-block and ignores it")
        if codec:
            log.warning("--codec is a JAX-builder knob; the native "
                        "builder writes raw blocks and ignores it")
        return (f"{require_binary('make_cpd_auto')}"
                f" --input {conf.xy_file} --partmethod {conf.partmethod}"
                f" --partkey {partkey} --workerid {wid}"
                f" --maxworker {conf.maxworker} --outdir {conf.outdir}")
    cmd = (f"{sys.executable} -m distributed_oracle_search_tpu.worker.build"
           f" --input {conf.xy_file} --partmethod {conf.partmethod}"
           f" --partkey {partkey} --workerid {wid}"
           f" --maxworker {conf.maxworker} --outdir {conf.outdir}")
    if chunk:
        cmd += f" --chunk {chunk}"
    if not resume:
        cmd += " --no-resume"
    if codec:
        cmd += f" --codec {codec}"
    repl = conf.effective_replication()
    if repl > 1:
        cmd += f" --replication {repl}"
    return cmd


def call_worker(wid: int, conf: ClusterConfig, chunk: int = 0,
                engine: str = "python", resume: bool = True,
                codec: str | None = None):
    """Launch one worker's build (parity: reference ``make_cpds.py:10-25``).

    Returns a Popen handle when the build runs as a tracked local
    subprocess, else None (tmux/ssh detached)."""
    host = conf.workers[wid]
    cmd = worker_build_cmd(wid, conf, chunk, engine, resume=resume,
                           codec=codec)
    log.info("launch build w%d on %s: %s", wid, host, cmd)
    # prefer_track: builds are finite jobs — await local ones so the index
    # manifest can be finalized when they all complete
    return launch(host, session_name("worker", wid), cmd,
                  projectdir=conf.projectdir, prefer_track=True)


def run_verify(conf: ClusterConfig) -> int:
    """Check-only integrity pass: digest/shape-verify every manifest
    block in place, print the report, exit 0/3/4 (clean / degraded /
    corrupt — ``process_query``'s convention)."""
    from ..data.formats import xy_node_count
    from ..models.cpd import read_manifest, verify_index, verify_exit_code
    from ..parallel.partition import DistributionController

    # verify against the manifest's own block_size and replication (a
    # worker.build --block-size or replicated index is still a valid
    # index); the partition quadruple is still cross-checked against
    # the conf
    dc_kw = {}
    try:
        man = read_manifest(conf.outdir)
        bs = int(man.get("block_size", 0))
        if bs > 0:
            dc_kw["block_size"] = bs
        repl = int(man.get("replication", 1))
        if repl > 1:
            dc_kw["replication"] = repl
    except (OSError, ValueError):
        pass            # verify_index will report the unusable manifest
    try:
        dc = DistributionController(conf.partmethod, conf.partkey,
                                    conf.maxworker,
                                    xy_node_count(conf.xy_file), **dc_kw)
    except ValueError as e:
        # e.g. the manifest records replication > this conf's
        # maxworker: a manifest/conf mismatch is the contract's exit 4
        # (fatal), never a traceback
        log.error("verify fatal: %s", e)
        print(json.dumps({"index": conf.outdir, "exit_code": 4,
                          "fatal": str(e)}))
        return 4
    report = verify_index(conf.outdir, dc=dc)
    for fname in report["missing"]:
        log.error("missing block: %s", fname)
    for ent in report["corrupt"]:
        log.error("corrupt block: %s (%s)", ent["file"], ent["reason"])
    if report.get("fatal"):
        log.error("verify fatal: %s", report["fatal"])
    code = verify_exit_code(report)
    print(json.dumps({"index": conf.outdir, "exit_code": code,
                      **{k: report[k] for k in
                         ("total", "ok", "unverified", "missing",
                          "corrupt")},
                      **({"fatal": report["fatal"]}
                         if report.get("fatal") else {})}))
    return code


def run_scrub(conf: ClusterConfig, args) -> int:
    """``--scrub``: repeat the ``--verify`` check-only pass on a
    cadence and exit with the WORST code any pass produced (0 clean /
    3 degraded / 4 corrupt — degradation seen once is degradation,
    even if a later pass healed it out of view). ``--scrub-passes 0``
    repeats until interrupted; the interrupt still reports honestly."""
    import time

    worst = passes = 0
    budget = max(0, int(getattr(args, "scrub_passes", 1)))
    try:
        while True:
            worst = max(worst, run_verify(conf))
            passes += 1
            log.info("scrub pass %d done (worst exit so far: %d)",
                     passes, worst)
            if budget and passes >= budget:
                break
            time.sleep(max(0.0, float(getattr(args, "scrub_interval",
                                              60.0))))
    except KeyboardInterrupt:
        log.info("scrub interrupted after %d pass(es)", passes)
    return worst


def run_delta(conf: ClusterConfig, args) -> int:
    """Delta rebuild (``--delta-from OLD_INDEX --diff FUSED``): old
    index + fused diff epoch → a new epoch-tagged index bit-identical
    to a from-scratch build on the retimed graph, recomputing only the
    rows the changed edges can affect (``models.cpd.delta_build_index``
    — untouched blocks byte-copy with their journaled digests). Exit 0
    on success, 4 when the old index is unusable."""
    from ..data.graph import Graph
    from ..models.cpd import delta_build_index, read_manifest
    from ..parallel.partition import DistributionController

    if not args.diff:
        log.error("--delta-from needs the fused diff file (--diff)")
        return 2
    # honor the old manifest's block_size/replication like --verify (a
    # worker.build --block-size index delta-rebuilds consistently)
    dc_kw = {}
    try:
        man = read_manifest(args.delta_from)
        bs = int(man.get("block_size", 0))
        if bs > 0:
            dc_kw["block_size"] = bs
        repl = int(man.get("replication", 1))
        if repl > 1:
            dc_kw["replication"] = repl
    except (OSError, ValueError) as e:
        log.error("delta fatal: no readable manifest in %s: %s",
                  args.delta_from, e)
        print(json.dumps({"index": args.delta_from, "exit_code": 4,
                          "fatal": str(e)}))
        return 4
    graph = Graph.from_xy(conf.xy_file)
    dc = DistributionController(conf.partmethod, conf.partkey,
                                conf.maxworker, graph.n, **dc_kw)
    report = delta_build_index(
        graph, dc, args.delta_from, args.diff,
        epoch=getattr(args, "delta_epoch", None), chunk=args.chunk,
        resume=not getattr(args, "no_resume", False))
    print(json.dumps({"exit_code": 0, **report}))
    return 0


def run_tpu(conf: ClusterConfig, args) -> None:
    """In-process sharded build over the mesh."""
    from ..parallel.multihost import initialize_from_conf
    initialize_from_conf(conf)

    import jax
    if jax.process_count() == 1:
        # debris from killed builds; skipped multi-controller (another
        # process may have an atomic write in flight in the shared dir)
        sweep_stale_artifacts(conf.outdir)

    from ..data.graph import Graph
    from ..models.cpd import CPDOracle
    from ..parallel.mesh import mesh_from_config
    from ..parallel.partition import DistributionController

    graph = Graph.from_xy(conf.xy_file)
    dc = DistributionController(conf.partmethod, conf.partkey,
                                conf.maxworker, graph.n)
    mesh = mesh_from_config(conf)
    oracle = CPDOracle(graph, dc, mesh=mesh)
    oracle.build(chunk=args.chunk)
    oracle.save(conf.outdir, codec=getattr(args, "codec", None))
    print(f"built sharded CPD for {graph.n} nodes over "
          f"{conf.maxworker} mesh shards -> {conf.outdir}")


def run_host(conf: ClusterConfig, args) -> None:
    # sweep BEFORE any worker launches: once builds are running, their
    # own in-flight *.tmp files must not be swept out from under them
    sweep_stale_artifacts(conf.outdir)
    resume = not getattr(args, "no_resume", False)
    procs = []
    for wid in range(conf.maxworker):
        if args.worker != -1 and wid != args.worker:
            continue
        proc = call_worker(wid, conf, chunk=args.chunk, engine=args.engine,
                           resume=resume,
                           codec=getattr(args, "codec", None))
        if proc is not None:
            procs.append((wid, proc))
    failures = 0
    for wid, proc in procs:
        if proc.wait() != 0:
            log.error("worker %d build failed (rc=%d)", wid, proc.returncode)
            failures += 1
    if procs and not failures and args.worker == -1:
        # all local builds done -> finalize the index manifest
        from ..data.formats import xy_node_count
        from ..models.cpd import (
            anti_entropy, build_replica_shards, write_index_manifest,
        )
        from ..parallel.partition import DistributionController
        dc = DistributionController(conf.partmethod, conf.partkey,
                                    conf.maxworker,
                                    xy_node_count(conf.xy_file),
                                    replication=conf
                                    .effective_replication())
        graph = None
        if dc.replication > 1:
            # backstop for builders that only emit primaries (the
            # native engine, or replica builds that raced a peer's
            # primary): materialize replica sets with files still
            # MISSING on disk (existence scan only — the workers'
            # ledgers already digest-verified what they wrote, and the
            # anti-entropy pass below digest-checks everything once)
            from ..models.cpd import shard_block_name
            from ..data.graph import Graph as _Graph
            graph = _Graph.from_xy(conf.xy_file)
            bs = dc.block_size
            for host in range(conf.maxworker):
                missing = any(
                    not os.path.exists(os.path.join(
                        conf.outdir,
                        shard_block_name(shard, bid,
                                         dc.replica_rank(shard, host))))
                    for shard in dc.replica_shards(host)[1:]
                    for bid in range((dc.n_owned(shard) + bs - 1) // bs))
                if missing:
                    build_replica_shards(graph, dc, host, conf.outdir,
                                         chunk=args.chunk)
        manifest = write_index_manifest(conf.outdir, dc)
        if dc.replication > 1:
            report = anti_entropy(conf.outdir, dc, graph=graph,
                                  manifest=manifest)
            print(f"anti-entropy: {report['checked']} replica "
                  f"block(s) cross-checked, "
                  f"{len(report['mismatched'])} divergent, "
                  f"{len(report['healed'])} healed")
        print(f"index complete -> {conf.outdir}")
    if failures:
        raise SystemExit(f"{failures} worker build(s) failed")


def main(argv=None) -> int:
    args = parse_args(argv, prog="make_cpds")
    set_verbosity(args.verbose)
    if args.test:
        import jax

        from ..data.synth import ensure_synth_dataset

        # size the canned config to the local device count, like
        # process_query's test mode — the two must build/read the same index
        conf = test_config(n_workers=len(jax.devices()))
        ensure_synth_dataset(os.path.dirname(conf.xy_file) or "./data")
    else:
        conf = ClusterConfig.load(args.c)
    if getattr(args, "scrub", False):
        return run_scrub(conf, args)
    if getattr(args, "verify", False):
        return run_verify(conf)
    if getattr(args, "delta_from", None):
        return run_delta(conf, args)
    if args.backend == "tpu" or (args.backend == "auto" and conf.is_tpu):
        run_tpu(conf, args)
    else:
        run_host(conf, args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
