"""Resident query-server launcher: the framework's ``make_fifos.py``.

Role parity with reference P3 (SURVEY.md §2.1): for each worker, start a
resident query server that loads the graph, the first diff, and its CPD
shard, then blocks on its command FIFO ``/tmp/worker<wid>.fifo``.

* host partmethods: one ``worker.server`` process per worker — ssh +
  detached tmux for remote hosts (reference ``make_fifos.py:22``), tracked
  local subprocess otherwise. Session name ``fifo-<wid>``.
* ``partmethod=tpu``: servers are unnecessary — the campaign driver
  (``cli.process_query``) answers in-process on the mesh; this launcher
  says so and exits 0 (launch host-mode servers with ``--backend host`` if
  you want FIFO transport against CPU shards anyway).

The default algorithm is table-search, as in the reference (hard-coded
there, reference ``make_fifos.py:20``); ``--alg astar`` launches
hscale/fscale weighted-A* servers, ``--alg ch`` (native engine only)
contraction-hierarchy servers — the congestion-free family of the
reference's TODO (reference ``README.md:133``).
"""

from __future__ import annotations

import sys

from .args import parse_args
from ..transport.launch import launch, session_name
from ..utils.config import ClusterConfig, test_config
from ..utils.log import get_logger, set_verbosity

log = get_logger(__name__)


def worker_server_cmd(wid: int, conf_path: str, verbose: int = 0,
                      engine: str = "python",
                      conf: ClusterConfig | None = None,
                      alg: str = "table-search") -> str:
    if engine == "native":
        from ..utils.nativebin import require_binary
        assert conf is not None
        partkey = (" ".join(str(b) for b in conf.partkey)
                   if isinstance(conf.partkey, (list, tuple))
                   else str(conf.partkey))
        diff = conf.diffs[0] if conf.diffs else "-"
        return (f"{require_binary('fifo_auto')}"
                f" --input {conf.xy_file} {diff}"
                f" --partmethod {conf.partmethod} --partkey {partkey}"
                f" --workerid {wid} --maxworker {conf.maxworker}"
                f" --outdir {conf.outdir} --alg {alg}")
    cmd = (f"{sys.executable} -m distributed_oracle_search_tpu.worker.server"
           f" -c {conf_path} --workerid {wid} --alg {alg}")
    if verbose:
        cmd += " -" + "v" * verbose
    return cmd


def call_worker(wid: int, conf: ClusterConfig, conf_path: str,
                verbose: int = 0, engine: str = "python",
                alg: str = "table-search"):
    host = conf.workers[wid]
    cmd = worker_server_cmd(wid, conf_path, verbose, engine, conf, alg=alg)
    log.info("launch server w%d on %s: %s", wid, host, cmd)
    return launch(host, session_name("fifo", wid), cmd,
                  projectdir=conf.projectdir)


def main(argv=None) -> int:
    args = parse_args(argv, prog="make_fifos")
    set_verbosity(args.verbose)
    if args.test:
        conf, conf_path = test_config(), None
    else:
        conf, conf_path = ClusterConfig.load(args.c), args.c
    if args.backend != "host" and conf.is_tpu:
        print("partmethod=tpu: queries run in-process on the device mesh; "
              "no resident servers needed. (Use --backend host to force "
              "FIFO servers.)")
        return 0
    if conf_path is None:
        raise SystemExit("host-mode servers need a conf file (-c), "
                         "not -t test mode")
    if args.alg == "ch" and args.engine != "native":
        raise SystemExit("--alg ch is served by the native engine "
                         "(contraction hierarchies, native/src/ch.hpp); "
                         "add --engine native")
    if args.supervise:
        from ..transport.launch import LOCAL_HOSTS
        from ..worker.supervisor import supervise_forever
        if args.engine != "python":
            raise SystemExit("--supervise manages python worker.server "
                             "subprocesses (the native engine has no "
                             "supervised launch yet)")
        remote = [h for h in conf.workers if h not in LOCAL_HOSTS]
        if remote:
            raise SystemExit(f"--supervise is local-only; conf names "
                             f"remote hosts {sorted(set(remote))} — run "
                             f"the supervisor on each worker host")
        return supervise_forever(conf, conf_path, alg=args.alg,
                                 obs_port=getattr(args, "obs_port",
                                                  None),
                                 traffic_dir=getattr(args,
                                                     "traffic_dir",
                                                     None))
    procs = []
    for wid in range(conf.maxworker):
        if args.worker != -1 and wid != args.worker:
            continue
        proc = call_worker(wid, conf, conf_path, args.verbose,
                           engine=args.engine, alg=args.alg)
        if proc is not None:
            procs.append((wid, proc))
    print(f"launched {conf.maxworker if args.worker == -1 else 1} "
          f"query server(s)")
    # tracked local subprocesses are intentionally NOT awaited: servers are
    # resident. Handles returned for embedders/tests via module state.
    main.procs = procs  # type: ignore[attr-defined]
    return 0


if __name__ == "__main__":
    sys.exit(main())
