"""Fleet observability CLI (``dos-obs``).

Head-side tooling over the artifacts and endpoints the obs plane
produces (the merge/compare logic lives in :mod:`..obs.fleet`):

* ``dos-obs merge-metrics [-o fleet_metrics.json] SNAPSHOT...`` —
  merge per-worker ``obs_metrics.json`` snapshots (shipped over the
  NFS data plane by ``--metrics-dump`` / campaign artifact dirs) into
  one labeled fleet document: per-worker sections plus summed fleet
  counters/gauges/histograms. ``--label`` overrides the path-derived
  worker labels (repeatable, positional order).
* ``dos-obs merge-traces -o merged.json TRACE_OR_DIR...`` — merge a
  campaign head's ``--trace`` file with worker ``.trace`` span
  sidecars (directories are globbed for ``*.trace``) into ONE
  Perfetto-loadable timeline.
* ``dos-obs top --endpoints host:port[,host:port...]`` — poll each
  endpoint's ``/statusz`` and render the live fleet table (queue
  depths, open breakers, hedge rate, worker batches/failures);
  ``--watch N`` refreshes every N seconds until interrupted.
* ``dos-obs bench-diff [--dir .]`` — compare the newest
  ``BENCH_r*.json`` against the previous one with per-key tolerances
  (``--tolerance``, ``--key-tolerance key=frac``) and exit non-zero on
  regression — the bench trajectory as a CI gate instead of a log.
* ``dos-obs slo --endpoint host:port`` — fetch the head's ``/slo``
  burn-rate page and render each spec's fast/slow burn + alert state;
  exits non-zero while any spec is alerting (scriptable as a deploy
  gate).
* ``dos-obs record --dir TAPE`` — summarize a flight-recorder ring
  (segments, records, time span).
* ``dos-obs replay --dir TAPE [--trace DIR...]`` — reconstruct the
  incident timeline from the tape (events + ticks, optionally merged
  with Perfetto spans by trace id) in timestamp order.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from ..obs import fleet
from ..utils.log import get_logger, set_verbosity

log = get_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dos-obs", description=__doc__.splitlines()[0])
    p.add_argument("-v", "--verbose", action="count", default=0)
    sub = p.add_subparsers(dest="cmd", required=True)

    mm = sub.add_parser("merge-metrics",
                        help="merge per-worker obs_metrics.json "
                             "snapshots into fleet_metrics.json")
    mm.add_argument("snapshots", nargs="+", help="snapshot JSON paths")
    mm.add_argument("-o", "--output", default="fleet_metrics.json")
    mm.add_argument("--label", action="append", default=[],
                    help="worker label per positional snapshot "
                         "(default: derived from the path)")

    mt = sub.add_parser("merge-traces",
                        help="merge head trace + worker .trace "
                             "sidecars into one Perfetto timeline")
    mt.add_argument("traces", nargs="+",
                    help="trace files, sidecars, or dirs (globs "
                         "*.trace)")
    mt.add_argument("-o", "--output", required=True)

    tp = sub.add_parser("top", help="live fleet table from /statusz")
    tp.add_argument("--endpoints", required=True,
                    help="comma-separated host:port list")
    tp.add_argument("--watch", type=float, default=0.0,
                    help="refresh every N seconds (0 = once)")
    tp.add_argument("--timeout", type=float, default=3.0)

    bd = sub.add_parser("bench-diff",
                        help="gate the newest BENCH_r*.json against "
                             "the previous round")
    bd.add_argument("records", nargs="*",
                    help="explicit OLD NEW record paths (default: the "
                         "two newest in --dir)")
    bd.add_argument("--dir", default=".",
                    help="where BENCH_r*.json live")
    bd.add_argument("--tolerance", type=float,
                    default=fleet.DEFAULT_TOLERANCE,
                    help="allowed fractional slack per key")
    bd.add_argument("--key-tolerance", action="append", default=[],
                    metavar="KEY=FRAC",
                    help="per-key tolerance override (repeatable)")
    bd.add_argument("--waive", action="append", default=[],
                    metavar="KEY=ROUND",
                    help="record a per-key baseline waiver: accept "
                         "KEY's regression when the NEW record is "
                         "round ROUND (e.g. scale_build_rows_per_sec"
                         "=r05); written to BENCH_WAIVERS.json in "
                         "--dir so the acceptance is reviewed with "
                         "the diff (repeatable)")
    bd.add_argument("--waive-reason", default="",
                    help="why the waived regression is accepted "
                         "(recorded alongside --waive)")

    sl = sub.add_parser("slo", help="burn-rate page from the head's "
                                    "/slo endpoint")
    sl.add_argument("--endpoint", required=True, help="host:port")
    sl.add_argument("--watch", type=float, default=0.0,
                    help="refresh every N seconds (0 = once)")
    sl.add_argument("--timeout", type=float, default=3.0)

    rc = sub.add_parser("record", help="summarize a flight-recorder "
                                       "tape directory")
    rc.add_argument("--dir", required=True, help="tape directory")

    rp = sub.add_parser("replay", help="reconstruct an incident "
                                       "timeline from a tape")
    rp.add_argument("--dir", required=True, help="tape directory")
    rp.add_argument("--since", type=float, default=None,
                    help="drop records before this unix timestamp")
    rp.add_argument("--until", type=float, default=None,
                    help="drop records after this unix timestamp")
    rp.add_argument("--trace", action="append", default=[],
                    help="Perfetto trace file/dir to merge spans from "
                         "by trace id (repeatable)")
    rp.add_argument("--events-only", action="store_true",
                    help="hide telemetry ticks, show events only")
    return p


def _cmd_merge_metrics(args) -> int:
    inputs = fleet.load_snapshot_files(args.snapshots,
                                       labels=args.label)
    doc = fleet.merge_snapshots(inputs)
    from ..utils.atomicio import atomic_write_bytes
    atomic_write_bytes(args.output,
                       (json.dumps(doc, indent=1) + "\n").encode())
    print(f"merged {doc['n_workers']} snapshot(s) -> {args.output}")
    return 0


def _cmd_merge_traces(args) -> int:
    n = fleet.merge_traces(args.traces, args.output)
    print(f"merged {n} event(s) -> {args.output} "
          "(open at https://ui.perfetto.dev)")
    return 0


def _cmd_top(args) -> int:
    endpoints = [e.strip() for e in args.endpoints.split(",")
                 if e.strip()]
    try:
        while True:
            # Ctrl-C must exit cleanly from ANYWHERE in the refresh —
            # the polls themselves block up to timeout_s per
            # unreachable endpoint, not just the sleep
            statuses = {ep: fleet.fetch_statusz(ep,
                                                timeout_s=args.timeout)
                        for ep in endpoints}
            print(fleet.render_top(statuses))
            if args.watch <= 0:
                return 0
            time.sleep(args.watch)
            print()
    except KeyboardInterrupt:
        return 0


def _cmd_bench_diff(args) -> int:
    if args.records:
        if len(args.records) != 2:
            raise SystemExit("bench-diff takes exactly OLD NEW when "
                             "records are given explicitly")
        old_path, new_path = args.records
    else:
        records = fleet.find_bench_records(args.dir)
        if len(records) < 2:
            print(f"bench-diff: fewer than two BENCH_r*.json in "
                  f"{args.dir}; nothing to compare")
            return 0
        new_path = records[-1]
        # compare against the nearest PREVIOUS round that actually
        # carries numbers: an unparseable record (the r04 overflow
        # failure mode) must not mask a regression by matching nothing
        old_path = next(
            (p for p in reversed(records[:-1]) if fleet.bench_numbers(p)),
            records[-2])
    key_tol = {}
    for spec in args.key_tolerance:
        key, _, frac = spec.partition("=")
        try:
            key_tol[key] = float(frac)
        except ValueError:
            raise SystemExit(f"bad --key-tolerance {spec!r} "
                             "(want KEY=FRACTION)")
    # record any --waive KEY=ROUND pairs first, then gate with the full
    # recorded set: the waiver mechanism accepts a REVIEWED baseline
    # shift (the file lands in the repo diff) without deleting history
    old_nums = fleet.bench_numbers(old_path)
    new_nums = fleet.bench_numbers(new_path)
    new_round = fleet.bench_round(new_path)
    for spec in args.waive:
        key, _, rnd = spec.partition("=")
        if not key or not rnd:
            raise SystemExit(f"bad --waive {spec!r} (want KEY=ROUND, "
                             "e.g. scale_build_rows_per_sec=r05)")
        if rnd != new_round:
            # a waiver only fires when the NEWEST record is its round;
            # recording one that cannot apply would print 'recorded'
            # and then gate anyway — reject it up front
            raise SystemExit(
                f"--waive {spec!r} cannot apply: the newest record is "
                + (f"round {new_round!r}" if new_round else
                   f"{new_path!r} (not a canonical BENCH_rNN name, so "
                   "no waiver can match it)"))
        entry = {"reason": args.waive_reason}
        if key in old_nums:
            entry["old"] = old_nums[key]
        if key in new_nums:
            entry["new"] = new_nums[key]
        fleet.record_waiver(args.dir, key, rnd, entry)
        print(f"  recorded waiver {key}={rnd} in "
              f"{fleet.WAIVER_FILE}")
    out = fleet.compare_bench(old_path, new_path,
                              tolerance=args.tolerance,
                              key_tolerances=key_tol,
                              waivers=fleet.load_waivers(args.dir))
    print(f"bench-diff: {out['old']} -> {out['new']} "
          f"({out['checked']} shared keys)")
    for e in out["improved"]:
        print(f"  + {e['key']}: {e['old']:g} -> {e['new']:g} "
              f"(x{e['ratio']:.2f})")
    for e in out["waived"]:
        reason = e.get("waiver", {}).get("reason", "")
        print(f"  ~ WAIVED {e['key']}: {e['old']:g} -> {e['new']:g} "
              f"(x{e['ratio']:.2f}, recorded for "
              f"{e['waiver'].get('round', '?')}"
              + (f": {reason}" if reason else "") + ")")
    for e in out["regressions"]:
        print(f"  ! REGRESSION {e['key']}: {e['old']:g} -> "
              f"{e['new']:g} (x{e['ratio']:.2f}, "
              f"{e['direction']}-is-better, tol {e['tolerance']:.0%})")
    if out["regressions"]:
        return 1
    print("  no regressions")
    return 0


def _render_slo(payload: dict) -> tuple[str, bool]:
    """The ``/slo`` payload as a table; second value = any alert."""
    if "error" in payload and not any(
            isinstance(v, dict) for v in payload.values()):
        return f"slo: {payload['error']}", False
    hdr = (f"{'spec':24s} {'kind':12s} {'objective':>9s} "
           f"{'fast burn':>9s} {'slow burn':>9s}  state")
    lines = [hdr, "-" * len(hdr)]
    alerting = False
    for name, s in sorted(payload.items()):
        if not isinstance(s, dict):
            continue

        def _b(v):
            return f"{v:9.2f}" if isinstance(v, (int, float)) else (
                " " * 8 + "-")

        state = "ALERT" if s.get("alerting") else "ok"
        alerting = alerting or bool(s.get("alerting"))
        lines.append(
            f"{name:24s} {str(s.get('kind', '')):12s} "
            f"{s.get('objective', 0):9.4f} {_b(s.get('fast_burn'))} "
            f"{_b(s.get('slow_burn'))}  {state}")
    return "\n".join(lines), alerting


def _cmd_slo(args) -> int:
    try:
        while True:
            table, alerting = _render_slo(
                fleet.fetch_json(args.endpoint, "/slo",
                                 timeout_s=args.timeout))
            print(table)
            if args.watch <= 0:
                # scriptable: a deploy gate can `dos-obs slo && push`
                return 1 if alerting else 0
            time.sleep(args.watch)
            print()
    except KeyboardInterrupt:
        return 0


def _cmd_record(args) -> int:
    from ..obs import recorder as obs_recorder

    records = obs_recorder.replay(args.dir)
    segments = obs_recorder.segment_paths(args.dir)
    events = [r for r in records if r.get("rec") == "event"]
    ticks = [r for r in records if r.get("rec") == "tick"]
    print(f"tape {args.dir}: {len(segments)} segment(s), "
          f"{len(records)} record(s) ({len(events)} event(s), "
          f"{len(ticks)} tick(s))")
    if records:
        t0, t1 = records[0]["ts"], records[-1]["ts"]
        print(f"  span: {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(t0))}"
              f" .. {time.strftime('%H:%M:%S', time.localtime(t1))} "
              f"({t1 - t0:.1f}s)")
    kinds = {}
    for r in events:
        kinds[r.get("kind", "?")] = kinds.get(r.get("kind", "?"), 0) + 1
    for kind, n in sorted(kinds.items()):
        print(f"  {kind:20s} {n}")
    return 0


def _cmd_replay(args) -> int:
    from ..obs import recorder as obs_recorder

    records = obs_recorder.replay(args.dir, since=args.since,
                                  until=args.until)
    if args.events_only:
        records = [r for r in records if r.get("rec") != "tick"]
    print(obs_recorder.render_timeline(records,
                                       trace_paths=args.trace or None))
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    set_verbosity(args.verbose)
    return {"merge-metrics": _cmd_merge_metrics,
            "merge-traces": _cmd_merge_traces,
            "top": _cmd_top,
            "bench-diff": _cmd_bench_diff,
            "slo": _cmd_slo,
            "record": _cmd_record,
            "replay": _cmd_replay}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
