"""Node reordering — the reference's ``--order`` NodeOrdering, as a tool.

The reference accepts ``--order <file>`` to overwrite warthog's internal
NodeOrdering (reference ``args.py:119``). In this framework node ids are
load-bearing for BUILD SPEED: the shift-coverage and fast-sweeping gates
key on id locality (``data/graph.py`` ``shift_split``/``grid_split``), so
an arbitrarily-ordered real graph (e.g. DIMACS) should be reordered once,
up front, by BFS or reverse Cuthill–McKee.

Reordering relabels nodes EVERYWHERE, so this tool rewrites the whole
dataset consistently — graph, scenario, diffs — plus a ``.order`` file
(line k = old id of new node k) for mapping external ids later. Build and
serve then agree by construction, the same way the reference keeps the
partmethod quadruple consistent by passing it to every binary.

    python -m distributed_oracle_search_tpu.cli.reorder \
        --input ny.xy --order rcm -o ny-rcm.xy \
        [--scen full.scen reordered.scen] [--diff ny.diff ny-rcm.diff]

``--order`` takes ``bfs``, ``rcm``, or a file of node ids (one per line,
line k = old id of new node k — the same format this tool emits).
"""

from __future__ import annotations

import numpy as np

from ..data.formats import (
    read_diff, read_scen, write_diff, write_scen, write_xy,
)
from ..data.graph import Graph


def resolve_order(graph: Graph, spec: str) -> np.ndarray:
    """``bfs`` / ``rcm`` / path-to-file → permutation (new → old)."""
    if spec == "bfs":
        return graph.bfs_order()
    if spec == "rcm":
        return graph.rcm_order()
    perm = np.loadtxt(spec, dtype=np.int64, ndmin=1)
    if len(perm) != graph.n:
        raise ValueError(
            f"order file {spec} has {len(perm)} ids, graph has {graph.n}")
    return perm


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--input", required=True, help="input .xy graph")
    p.add_argument("--order", required=True,
                   help="bfs | rcm | order-file (line k = old id of new "
                        "node k)")
    p.add_argument("-o", "--output", required=True, help="output .xy")
    p.add_argument("--scen", nargs=2, metavar=("IN", "OUT"), default=None,
                   help="also remap a scenario file")
    p.add_argument("--diff", nargs=2, metavar=("IN", "OUT"), default=None,
                   action="append",
                   help="also remap a diff file (repeatable)")
    args = p.parse_args(argv)

    g = Graph.from_xy(args.input)
    perm = resolve_order(g, args.order)
    g2 = g.reorder(perm)
    inv = np.empty(g.n, np.int64)
    inv[perm] = np.arange(g.n)

    write_xy(args.output, g2.xs, g2.ys, g2.src, g2.dst, g2.w)
    np.savetxt(args.output + ".order", perm, fmt="%d")
    if args.scen:
        q = read_scen(args.scen[0])
        write_scen(args.scen[1], inv[q],
                   comment=f"reordered by {args.order}")
    for pair in (args.diff or []):
        dsrc, ddst, dw = read_diff(pair[0])
        write_diff(pair[1], inv[dsrc], inv[ddst], dw)
    from ..ops.shift_relax import split_coverage

    _, w_shift, _, w_left = g2.shift_split()
    cov = split_coverage(w_shift, w_left)
    print(f"{args.output}: {g2.n} nodes reordered ({args.order}); "
          f"shift coverage {cov:.1%}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
