"""Head-node drivers: CLI parity with the reference's entry points.

* ``make_cpds``      — distributed CPD precompute (reference P2)
* ``make_fifos``     — resident query-server launch (reference P3)
* ``process_query``  — the query campaign (reference P4)
* ``offline``        — single-machine legacy driver (reference P6)
* ``args``           — the shared flag surface (reference P1)
"""

from .args import build_parser, get_time_ns, parse_args, process_filename

__all__ = ["build_parser", "get_time_ns", "parse_args", "process_filename"]
