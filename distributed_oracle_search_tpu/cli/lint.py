"""``dos-lint``: the project-contract static analyzer.

Usage::

    dos-lint                      # lint the installed package
    dos-lint path/ other.py       # lint explicit files/dirs
    dos-lint --strict             # exit 1 on any unsuppressed finding
    dos-lint --json               # machine report (bench-diff gate
                                  # convention: ok/exit_code fields)
    dos-lint --list-rules         # the rule table
    dos-lint --select env-discipline,lock-scope
    dos-lint --disable jit-purity

Exit codes (shared convention with ``dos-obs bench-diff`` so CI can
chain both gates in one pipeline): 0 clean, 1 gate failed (findings,
``--strict`` or ``--json``), 2 usage error. Suppress individual sites
inline — justification mandatory::

    x = os.environ.get("DOS_X")  # dos-lint: disable=env-discipline -- why
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..analysis import (
    ALL_RULES, LintConfig, render_json, render_text, run_paths,
)


def default_target() -> str:
    """The installed package directory (self-lint default)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dos-lint", description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the package)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 when unsuppressed findings remain")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="JSON report on stdout (implies --strict exit "
                        "semantics: ok/exit_code mirror bench-diff)")
    p.add_argument("--select", default="",
                   help="comma-separated rules to run (default: all)")
    p.add_argument("--disable", default="",
                   help="comma-separated rules to skip")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings (text mode)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    return p


def _split(spec: str) -> tuple:
    return tuple(s.strip() for s in spec.split(",") if s.strip())


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.name:16s} {r.description}")
        return 0
    known = {r.name for r in ALL_RULES}
    select, disable = _split(args.select), _split(args.disable)
    for name in (*select, *disable):
        if name not in known:
            print(f"dos-lint: unknown rule {name!r} (see --list-rules)",
                  file=sys.stderr)
            return 2
    config = LintConfig(select=select, disable=disable)
    paths = args.paths or [default_target()]
    for p in paths:
        if not os.path.exists(p):
            print(f"dos-lint: no such path: {p}", file=sys.stderr)
            return 2
    findings, n_files = run_paths(paths, ALL_RULES, config)
    active = [f for f in findings if not f.suppressed]
    if args.as_json:
        print(json.dumps(render_json(findings, n_files), indent=1))
        return 1 if active else 0
    print(render_text(findings, n_files,
                      show_suppressed=args.show_suppressed))
    if args.strict and active:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
