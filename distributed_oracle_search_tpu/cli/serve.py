"""Always-on oracle service: the online counterpart of the campaign
drivers (``dos-serve``).

Where ``cli.process_query`` answers a whole scenario file and exits,
this entry point keeps a :class:`~..serving.ServingFrontend` resident
and feeds it from a line-protocol ingress (stdin by default; a unix
socket or a tailed file for external producers). Two backends:

* ``--backend inproc`` (default) — shard engines live in this process
  (one :class:`~..worker.engine.ShardEngine` per worker; missing CPD
  shards are built on first use so ``--test`` works from a bare
  checkout);
* ``--backend host`` — the campaign wire against resident
  ``worker.server`` processes (launch them with ``dos-make-fifos``),
  with the per-worker circuit breakers + background healing probes the
  campaign path uses; per-query answers return via the
  ``RuntimeConfig.results`` sidecar wire extension.

Serving knobs come from ``DOS_SERVE_*`` env vars, overridable by flags
(``--max-batch``, ``--max-wait-ms``, ``--queue-depth``,
``--cache-bytes``, ``--deadline-ms``). ``--metrics-dump PATH`` writes
the obs snapshot on shutdown — queue depths, batch-fill and
time-to-flush histograms, cache hit/miss counters, end-to-end request
latencies.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from ..obs import metrics as obs_metrics
from ..serving import (
    AutoDispatcher, EngineDispatcher, FifoDispatcher, RpcDispatcher,
    ServeConfig, ServingFrontend,
)
from ..serving import ingress
from ..transport import fifo as fifo_transport
from ..transport import resilience
from ..transport import rpc as rpc_transport
from ..transport.fifo import command_fifo_path
from ..transport.wire import RuntimeConfig
from ..utils.config import ClusterConfig, test_config
from ..utils.log import get_logger, set_verbosity

log = get_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="serve", description=__doc__.splitlines()[0])
    p.add_argument("-c", default="./example-cluster-conf.json",
                   help="cluster config JSON")
    p.add_argument("-t", "--test", action="store_true",
                   help="serve the canned synthetic dataset (builds "
                        "missing CPD shards in-process)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    p.add_argument("--backend", default="inproc",
                   choices=["inproc", "host"],
                   help="inproc: shard engines in this process; host: "
                        "FIFO wire to resident worker servers")
    p.add_argument("--alg", default="table-search",
                   choices=["table-search", "astar"],
                   help="serving algorithm (inproc backend)")
    p.add_argument("--diff", default=None,
                   help="active congestion diff (default: the conf's "
                        "first diff, '-' = free flow)")
    p.add_argument("--ingress", default="stdin",
                   choices=["stdin", "socket", "tail"],
                   help="where 's t' request lines come from")
    p.add_argument("--socket", default="/tmp/dos-serve.sock",
                   help="unix socket path (--ingress socket)")
    p.add_argument("--tail", default=None,
                   help="request file to follow (--ingress tail); "
                        "answers append to <file>.answers")
    p.add_argument("--queue-depth", type=int, default=None,
                   help="per-shard queue bound (DOS_SERVE_QUEUE_DEPTH)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="micro-batch flush size, power of two "
                        "(DOS_SERVE_MAX_BATCH)")
    p.add_argument("--max-wait-ms", type=float, default=None,
                   help="micro-batch wait bound (DOS_SERVE_MAX_WAIT_MS)")
    p.add_argument("--cache-bytes", type=int, default=None,
                   help="result-cache budget, 0 disables "
                        "(DOS_SERVE_CACHE_BYTES)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request deadline (DOS_SERVE_DEADLINE_MS)")
    p.add_argument("--traffic-dir", default=None,
                   help="diff segment stream directory: swap the "
                        "active congestion diff LIVE as epoch-tagged "
                        "segments land (no restart; scoped cache "
                        "invalidation)")
    p.add_argument("--traffic-spool", default=None,
                   help="where fused per-epoch diff files materialize "
                        "(default <traffic-dir>/fused; must be "
                        "worker-visible for --backend host)")
    p.add_argument("--metrics-dump", default="",
                   help="write a JSON metrics snapshot here on shutdown")
    p.add_argument("--obs-port", type=int, default=None,
                   help="serve live /metrics /healthz /statusz on this "
                        "port (0 = OS-assigned ephemeral; default off; "
                        "DOS_OBS_PORT env)")
    p.add_argument("--recorder-dir", default=None,
                   help="flight-recorder tape directory: keep a bounded "
                        "on-disk ring of telemetry ticks + structured "
                        "events for dos-obs replay (DOS_RECORDER_DIR "
                        "env; default off)")
    return p


def build_frontend(conf: ClusterConfig, args):
    """Frontend + (for the host backend) the breaker registry the
    caller must shut down."""
    sconf = ServeConfig.from_env(
        queue_depth=args.queue_depth, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, cache_bytes=args.cache_bytes,
        deadline_ms=args.deadline_ms)
    # the answer-integrity plane (DOS_SCRUB_* / DOS_AUDIT_* /
    # DOS_ANSWER_FP) — every default is off, in which case nothing is
    # constructed and the wire stays byte-identical legacy
    from ..integrity import IntegrityConfig
    icfg = IntegrityConfig.from_env()
    rconf = RuntimeConfig(answer_fp=icfg.answer_fp)
    diff = args.diff if args.diff is not None else (
        conf.diffs[0] if conf.diffs else "-")
    registry = None
    breaker_key = None
    if args.backend == "host":
        if conf.is_tpu:
            raise SystemExit(
                "--backend host needs host-mode workers; partmethod=tpu "
                "shards live on the device mesh (use --backend inproc)")
        # DOS_TRANSPORT selects the host-backend data plane: `fifo`
        # (default — the campaign wire, byte-identical legacy), `rpc`
        # (persistent multiplexed sockets, no per-batch files), `auto`
        # (rpc with sticky per-lane fifo fallback for mixed fleets)
        transport = rpc_transport.resolve_transport()
        if transport == "rpc":
            dispatcher = RpcDispatcher(conf)
            probe_fn = lambda key: rpc_transport.probe(  # noqa: E731
                key[1], host=key[0])
        elif transport == "auto":
            dispatcher = AutoDispatcher(conf)

            def probe_fn(key):
                st = rpc_transport.probe(key[1], host=key[0])
                if st is not None:
                    return st
                return fifo_transport.probe(
                    key[0], key[1],
                    command_fifo=command_fifo_path(key[1]),
                    nfs=conf.nfs)
        else:
            dispatcher = FifoDispatcher(conf)
            probe_fn = lambda key: fifo_transport.probe(  # noqa: E731
                key[0], key[1], command_fifo=command_fifo_path(key[1]),
                nfs=conf.nfs)
        if transport != "fifo":
            log.info("host backend data plane: DOS_TRANSPORT=%s",
                     transport)
        registry = resilience.BreakerRegistry(probe_fn=probe_fn)
        breaker_key = lambda wid: (conf.workers[wid], wid)  # noqa: E731
    else:
        dispatcher = EngineDispatcher(conf, alg=args.alg,
                                      build_missing=args.test)
    dc = dispatcher.dc if args.backend == "inproc" else _dc_for(conf)
    # elastic membership: a committed epoch's owner table (and any
    # in-flight migration's dual-read window) overrides the conf's
    # static identity; absent membership.json = the pre-elastic world
    from ..parallel import membership as fleet
    mstate = fleet.load_state(conf.outdir)
    if mstate is not None:
        dc = fleet.apply_state(dc, mstate)
        if args.backend == "inproc":
            dispatcher.dc = dc
    # the controller is wired even on a static fleet: its throttled
    # refresh() picks up a membership.json that appears AFTER startup,
    # so a long-lived serve observes later join/leave commits instead
    # of routing to drained workers forever (epoch 0 keeps the wire and
    # admission byte-identical — the epoch stamp is gated on nonzero)
    mc = fleet.MembershipController(conf, dc)
    if args.backend == "host":
        # a joined worker's id is past the conf's static roster;
        # resolve hosts (dispatch AND breaker keys) from the live
        # membership roster instead
        dispatcher.host_of = mc.host_of
        breaker_key = lambda wid: (mc.host_of(wid), wid)  # noqa: E731
    if mstate is not None:
        log.info("serving under membership epoch %d", mc.epoch)
    # live traffic: a segment stream turns the static --diff into the
    # BASE of a rolling fusion; the frontend's epoch pump swaps fused
    # epochs without restart
    traffic = None
    if getattr(args, "traffic_dir", None):
        from ..traffic import DiffEpochManager

        traffic = DiffEpochManager(args.traffic_dir, base_diff=diff,
                                   spool_dir=args.traffic_spool)
        log.info("live traffic enabled: stream %s, spool %s",
                 args.traffic_dir, traffic.spool)
    frontend = ServingFrontend(
        dc, dispatcher, sconf=sconf, rconf=rconf, diff=diff,
        registry=registry, breaker_key=breaker_key, membership=mc,
        traffic=traffic)
    # typed query families (mat/alt/rev) on the same frontend; the alt
    # planner loads the graph lazily on its first query
    from ..traffic import QueryFamilies
    if args.backend == "inproc":
        families = QueryFamilies(
            frontend, graph=dispatcher.graph, traffic=traffic,
            oracle=_mesh_mat_oracle(conf, dispatcher, traffic))
    else:
        from ..data.graph import Graph
        families = QueryFamilies(
            frontend,
            graph_provider=lambda: Graph.from_xy(conf.xy_file),
            traffic=traffic)
    _build_integrity(frontend, dispatcher, icfg, args.backend)
    return frontend, registry, families


def _build_integrity(frontend, dispatcher, icfg, backend: str) -> None:
    """Construct whatever slice of the integrity plane is enabled and
    hang it off the frontend (``frontend.auditor`` /
    ``frontend.scrubber`` — ``/statusz`` and the control daemon's
    providers read them there). With every knob at its default this
    constructs nothing."""
    if not icfg.any_enabled:
        return
    if icfg.scrub_interval_s > 0:
        if backend == "inproc":
            from ..integrity.scrub import TableScrubber

            # the dispatcher builds engines lazily on first dispatch;
            # re-listing every pass picks up late arrivals
            scrubber = TableScrubber(
                lambda: list(dispatcher._engines.values()),
                icfg.scrub_interval_s, icfg.scrub_blocks_per_pass)
            scrubber.start()
            frontend.scrubber = scrubber
            log.info("resident scrubber on: every %.1fs, %s blocks/pass",
                     icfg.scrub_interval_s,
                     icfg.scrub_blocks_per_pass or "all")
        else:
            log.warning("DOS_SCRUB_INTERVAL_S ignored: the host "
                        "backend's resident tables live in the worker "
                        "processes, not here")
    if icfg.audit_rate > 0:
        from ..integrity.audit import AnswerAuditor, make_reference_fn

        reference_fn = describe_fn = None
        if backend == "inproc":
            reference_fn = make_reference_fn(dispatcher.graph)

            def describe_fn(wid, via):
                eng = dispatcher._engines.get((int(wid), via))
                return {"codec": getattr(eng, "resident_codec", None)
                        } if eng is not None else {}
        frontend.auditor = AnswerAuditor(
            dispatcher, icfg.audit_rate, reference_fn=reference_fn,
            describe_fn=describe_fn,
            max_reference=icfg.audit_max_reference)
        log.info("answer audit on: %d per mille, reference lane %s",
                 icfg.audit_rate,
                 "available" if reference_fn else "unavailable")
    if icfg.answer_fp:
        log.info("answer fingerprints on: replies and cache entries "
                 "carry crc32 checks")


def _mesh_mat_oracle(conf: ClusterConfig, dispatcher, traffic=None):
    """``DOS_MESH_MAT``: load a mesh-resident oracle so the ``mat``
    family answers each row with ONE on-mesh collective
    (``CPDOracle.query_mat`` — walk + psum join on device) instead of
    one frontend future per target. Inproc backend only (the oracle
    needs the full index on the local mesh); any load failure logs and
    degrades to the fan-out/join path, never a startup outage.

    Disabled under live traffic (``--traffic-dir``): the epoch pump
    can PROMOTE delta-rebuilt indexes into the dispatcher's engines
    (``ShardEngine.promote_index``), and this oracle's startup table
    would keep serving old-regime rows re-priced under new fused
    weights — mat rows would silently diverge from the pair path, the
    exact regime promotion exists to eliminate."""
    from ..utils.env import env_flag

    if not env_flag("DOS_MESH_MAT", False):
        return None
    if traffic is not None:
        log.warning("DOS_MESH_MAT ignored under --traffic-dir: the "
                    "mesh oracle cannot follow epoch-promoted delta "
                    "indexes; mat serves via fan-out/join")
        return None
    try:
        from ..models.cpd import CPDOracle

        oracle = CPDOracle(dispatcher.graph, dispatcher.dc)
        oracle.load(conf.outdir)
        log.info("DOS_MESH_MAT: mat family serving via on-mesh "
                 "collectives (index %s)", conf.outdir)
        return oracle
    except Exception as e:  # noqa: BLE001 — an optimization path must
        # not take the serve down with it
        log.warning("DOS_MESH_MAT: cannot load mesh oracle from %s: %s "
                    "(mat serves via fan-out/join)", conf.outdir, e)
        return None


def _dc_for(conf: ClusterConfig):
    from ..data.formats import xy_node_count
    from ..parallel.partition import DistributionController

    return DistributionController(conf.partmethod, conf.partkey,
                                  conf.maxworker,
                                  xy_node_count(conf.xy_file),
                                  replication=conf
                                  .effective_replication())


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    set_verbosity(args.verbose)
    if args.test:
        from ..data.synth import ensure_synth_dataset

        # the canned tpu-partition config: contiguous shards that match
        # the checked-in synth index layout; the inproc backend serves
        # any partmethod (shard engines only need the block files)
        conf = test_config()
        ensure_synth_dataset(os.path.dirname(conf.xy_file) or "./data")
    else:
        conf = ClusterConfig.load(args.c)
    frontend, registry, families = build_frontend(conf, args)
    frontend.start()
    obs_srv = None
    head_pub = poller = slo_engine = recorder = daemon = None
    # graceful drain: SIGTERM (the orchestrator's stop signal) and
    # SIGINT both stop ingress — the event ends the socket/tail loops,
    # the exception unwinds a blocking stdin read — then the finally
    # block drains the bounded queues, flushes in-flight micro-batches
    # (frontend.stop: every admitted request is answered or shed, never
    # silently dropped), writes the final metrics dump, and exits 0.
    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        if stop_evt.is_set():
            return     # repeat signal mid-drain: keep draining
        log.info("received %s; stopping ingress and draining",
                 signal.Signals(signum).name)
        stop_evt.set()
        raise KeyboardInterrupt

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)
    try:
        # live observability plane (opt-in): /metrics answers Prometheus
        # text with the sliding-window p50/p95/p99 gauges + exemplars,
        # /healthz flips 503 once draining starts, /statusz reports
        # breaker + queue + replica + hedge state. Inside the try: a
        # bind failure (port taken) must drain the started frontend,
        # not leave its batcher threads running behind a traceback
        from ..obs import device as obs_device
        from ..obs import recorder as obs_recorder
        from ..obs import slo as obs_slo
        from ..obs import telemetry as obs_telemetry
        from ..obs import timeseries as obs_timeseries
        from ..obs.http import start_obs_server
        from ..utils.env import env_str
        # the fleet telemetry plane: workers push ticks here (telemetry
        # frames on the RPC lane, polled .telemetry sidecars on the
        # FIFO lane), the head publishes its OWN serve-side windows and
        # shed counters into the same store, and the SLO engine burns
        # budgets against the merged view. All of it optional: with
        # DOS_TELEMETRY_INTERVAL_S=0 the serve runs exactly as before.
        store = obs_timeseries.TimeseriesStore()
        recorder = None
        rec_dir = args.recorder_dir or env_str("DOS_RECORDER_DIR")
        if rec_dir:
            recorder = obs_recorder.FlightRecorder(rec_dir)
            obs_recorder.set_recorder(recorder)
        tele_ingest = obs_telemetry.TelemetryIngest(store,
                                                    recorder=recorder)
        rpc_transport.set_telemetry_sink(tele_ingest.ingest)
        poller = None
        if args.backend == "host":
            poller = obs_telemetry.SidecarPoller(
                os.path.dirname(command_fifo_path(0)) or ".",
                tele_ingest).start()
        head_pub = None
        if obs_telemetry.interval_s() > 0:
            head_pub = obs_telemetry.TelemetryPublisher(
                source="head", sinks=[tele_ingest.ingest]).start()
        slo_engine = obs_slo.SLOEngine(store).start()
        # closed-loop control (DOS_CONTROL=1): the policy daemon senses
        # this head's SLO burn, queues, breakers and worker telemetry,
        # and executes the brownout/quarantine/repair/warming ladder
        # against the same in-process handles. Off by default: nothing
        # is constructed and serving is byte-identical legacy.
        from ..control import maybe_daemon
        probe_fn = None
        if registry is not None and registry.probe_fn is not None:
            def probe_fn(wid):
                st = registry.probe_fn(frontend._breaker_key(wid))
                return st is not None and getattr(st, "ok", False)
        daemon = maybe_daemon(
            slo=slo_engine, frontend=frontend, registry=registry,
            membership=frontend.membership, ingest=tele_ingest,
            probe_fn=probe_fn, integrity=frontend.auditor,
            scrub_fn=(frontend.scrubber.scrub_now
                      if frontend.scrubber is not None else None))
        status_providers = {
            "serving": frontend.statusz,
            "device_programs": obs_device.snapshot,
            "telemetry": tele_ingest.statusz,
            "slo": slo_engine.statusz,
        }
        if daemon is not None:
            status_providers["control"] = daemon.statusz
        if (frontend.auditor is not None
                or frontend.scrubber is not None):
            def _integrity_status(fe=frontend):
                out = {}
                if fe.auditor is not None:
                    out["audit"] = fe.auditor.statusz()
                if fe.scrubber is not None:
                    out["scrub"] = fe.scrubber.statusz()
                return out
            status_providers["integrity"] = _integrity_status
        obs_srv = start_obs_server(
            args.obs_port,
            health_fn=lambda: {
                "ok": frontend._started and not frontend._closed,
                "role": "dos-serve", "backend": args.backend},
            status_providers=status_providers,
            slo_provider=slo_engine.payload)
        if args.ingress == "stdin":
            n = ingress.serve_stdin(frontend, families=families)
        elif args.ingress == "socket":
            ingress.serve_unix_socket(frontend, args.socket,
                                      stop=stop_evt, families=families)
            n = None
        else:
            if not args.tail:
                raise SystemExit("--ingress tail needs --tail FILE")
            n = ingress.tail_file(frontend, args.tail, stop=stop_evt,
                                  families=families)
        if n is not None:
            log.info("ingress closed after %d request(s)", n)
    except KeyboardInterrupt:
        log.info("interrupted; draining")
    finally:
        stop_evt.set()
        if daemon is not None:
            daemon.stop()
        frontend.stop()
        # integrity plane after the frontend: no new batches are being
        # served, so the auditor drains its queue tail and exits
        if frontend.auditor is not None:
            frontend.auditor.stop()
        if frontend.scrubber is not None:
            frontend.scrubber.stop()
        if obs_srv is not None:
            obs_srv.close()
        # telemetry plane teardown: stop the loops, detach the global
        # sinks (they outlive main() otherwise), seal the tape durably
        rpc_transport.set_telemetry_sink(None)
        for t in (head_pub, poller, slo_engine):
            if t is not None:
                t.stop()
        if recorder is not None:
            from ..obs import recorder as obs_recorder
            obs_recorder.set_recorder(None)
            recorder.close()
        if registry is not None:
            registry.shutdown()
        if args.metrics_dump:
            obs_metrics.REGISTRY.dump_json(args.metrics_dump)
        log.info("drained and stopped cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
