"""Partition oracle CLI: the framework's ``gen_distribute_conf``.

CLI + wire parity with reference C2 (SURVEY.md §2.2; invoked at reference
``process_query.py:46``)::

    python -m distributed_oracle_search_tpu.cli.gen_distribute_conf \
        --nodenum <int> --maxworker <int> \
        --partmethod <div|mod|alloc|tpu> --partkey <int...>

Stdout: one header line, then one CSV row per node — ``node,wid,bid,bidx``
(parsed by the reference driver at ``process_query.py:50-53``). A pure
function of its flags: the single source of truth that keeps build-time
sharding and query-time routing consistent. In-process callers should use
``parallel.DistributionController`` directly; this program exists for
interop with external tooling that shells out.
"""

from __future__ import annotations

import argparse
import sys

from ..parallel.partition import DistributionController


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--nodenum", type=int, required=True)
    p.add_argument("--maxworker", type=int, required=True)
    p.add_argument("--partmethod", required=True,
                   choices=["div", "mod", "alloc", "tpu"])
    p.add_argument("--partkey", type=int, nargs="+", default=[1])
    p.add_argument("--replication", type=int, default=None,
                   help="R-way replica placement: appends rep<r> "
                        "columns naming the worker hosting each node's "
                        "rank-r replica (default: DOS_REPLICATION or 1; "
                        "1 emits the legacy 4-column format)")
    return p


def main(argv=None) -> int:
    from ..utils.env import env_cast

    args = build_parser().parse_args(argv)
    partkey = args.partkey if args.partmethod == "alloc" else args.partkey[0]
    replication = args.replication
    if replication is None:
        # env policy: a malformed or out-of-range DOS_REPLICATION
        # degrades to the legacy table (the explicit flag still raises)
        replication = env_cast("DOS_REPLICATION", 1, int)
        if not 1 <= replication <= args.maxworker:
            replication = 1
    dc = DistributionController(args.partmethod, partkey, args.maxworker,
                                args.nodenum, replication=replication)
    try:
        print(dc.format_conf())
    except BrokenPipeError:  # downstream `| head` closed the pipe; not an error
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
