"""Single-machine driver: the framework's ``offline.py``.

Role parity with reference P6 (SURVEY.md §2.1): the no-cluster-conf entry
point — partitioning computed in Python (schemes ``all``/``mod``/``div``/
``alloc``/range + ``--sort``), one resident engine instead of a worker
fleet, a true local path without ssh, and ``--cutoff`` forcing the local
path for small batches. ``--debug`` forces single-threaded deterministic
repro (reference ``offline.py:143-147``).

Here the "resident engine" is in-process by default: a 1-shard CPD oracle on
the local device answers each part as one XLA call. If a resident FIFO
server is already listening on ``--fifo`` (started by hand or by
``make_fifos --backend host`` with a 1-worker conf), parts are sent through
the reference's FIFO protocol instead — same wire, same stats.

``make_parts`` is the executable spec of the partition schemes
(reference ``offline.py:36-67``), with its two known bugs fixed: the
``all`` scheme can no longer run off the end of the parts list, and
``alloc`` no longer clobbers its bounds list (SURVEY.md §2.1 quirks).
"""

from __future__ import annotations

import os
import sys

import numpy as np

from .args import parse_args, process_filename
from .process_query import output, runtime_config
from ..data.formats import read_diff, read_scen
from ..transport.fifo import send_with_retry
from ..transport.wire import Request, StatsRow, write_query_file
from ..utils.log import get_logger, set_verbosity
from ..utils.timer import Timer

log = get_logger(__name__)

DEFAULT_ANSWER_FIFO = "/tmp/warthog.fifo.answer"


def make_parts(reqs: np.ndarray, args, num_parts: int) -> list[np.ndarray]:
    """Split queries into parts (executable spec: reference
    ``offline.py:36-67``). Schemes:

    * ``all``  — group by destination, then greedily fill parts up to the
      target size (overflow opens a new part instead of walking off the
      list — the reference's bug);
    * ``mod``  — part = target % num_parts;
    * ``div``  — contiguous target ranges of equal width;
    * ``alloc``— explicit ascending bounds (``--alloc``), one per part;
    * default  — chunk the request list by range into equal counts.

    ``--sort`` then sorts each part by target (reference ``offline.py:219``).
    """
    reqs = np.asarray(reqs, np.int64)
    n = len(reqs)
    t = reqs[:, 1]
    parts: list[np.ndarray]
    if args.group == "all":
        size = max(1, -(-n // num_parts))
        parts = []
        cur: list[np.ndarray] = []
        cur_n = 0
        # group queries sharing a destination, keep groups intact
        order = np.argsort(t, kind="stable")
        bounds = np.nonzero(np.diff(t[order]))[0] + 1
        for grp in np.split(order, bounds):
            if cur_n >= size and cur:
                parts.append(reqs[np.concatenate(cur)])
                cur, cur_n = [], 0
            cur.append(grp)
            cur_n += len(grp)
        if cur:
            parts.append(reqs[np.concatenate(cur)])
    elif args.group == "mod":
        key = args.mod if args.mod else num_parts
        parts = [reqs[t % key == i] for i in range(key)]
    elif args.group == "div":
        key = args.div if args.div else max(1, -(-int(t.max() + 1) // num_parts))
        parts = [reqs[t // key == i] for i in range(-(-int(t.max() + 1) // key))]
    elif args.alloc is not None:
        bounds = np.asarray(args.alloc, np.int64)
        idx = np.searchsorted(bounds, t, side="right")
        if (idx == len(bounds)).any():
            # loud failure, matching DistributionController: silently
            # dropping out-of-range targets would shrink campaign totals
            bad = int(t[idx == len(bounds)][0])
            raise ValueError(
                f"alloc bounds {list(bounds)} do not cover target {bad}")
        parts = [reqs[idx == i] for i in range(len(bounds))]
    else:  # by range: equal-count chunks of the request list
        parts = [chunk for chunk in np.array_split(reqs, num_parts)]
    parts = [p for p in parts if len(p)]
    if args.sort:
        parts = [p[np.argsort(p[:, 1], kind="stable")] for p in parts]
    return parts


class LocalEngine:
    """One-shard in-process oracle over the whole graph (the offline
    driver's resident engine)."""

    def __init__(self, xy_file: str, outdir: str | None, chunk: int = 0):
        from ..data.graph import Graph
        from ..models.cpd import CPDOracle
        from ..parallel.mesh import make_mesh
        from ..parallel.partition import DistributionController
        import jax

        self.graph = Graph.from_xy(xy_file)
        dc = DistributionController("tpu", None, 1, self.graph.n)
        mesh = make_mesh(n_workers=1, devices=jax.devices()[:1])
        self.oracle = CPDOracle(self.graph, dc, mesh=mesh)
        loaded = False
        if outdir:
            try:
                self.oracle.load(outdir)
                loaded = True
            except FileNotFoundError:
                pass
        if not loaded:
            self.oracle.build(chunk=chunk)
            if outdir:
                self.oracle.save(outdir)

    def answer(self, part: np.ndarray, args, w_query) -> list:
        with Timer() as search:
            cost, plen, fin = self.oracle.query(
                part, w_query=w_query, k_moves=args.k_moves)
        row = StatsRow(
            n_expanded=int(plen.sum()), n_touched=len(part),
            plen=int(plen.sum()), finished=int(fin.sum()),
            t_astar=search.interval, t_search=search.interval)
        return row.as_list(size=len(part))


def send_fifo(part: np.ndarray, args, diff: str, nfs: str) -> list:
    """Send one part through the resident server's FIFO pair (reference
    ``offline.py:70-82`` local path — no ssh)."""
    with Timer() as prep:
        qfile = os.path.join(nfs, f"query.offline{os.getpid()}")
        write_query_file(qfile, part)
    req = Request(runtime_config(args), qfile,
                  DEFAULT_ANSWER_FIFO, diff)
    row = send_with_retry("localhost", req, args.fifo)
    return row.as_list(t_prepare=prep.interval, size=len(part))


def main(argv=None) -> int:
    args = parse_args(argv, prog="offline")
    set_verbosity(args.verbose)
    if args.debug:
        args.omp, args.verbose = 1, max(args.verbose, 2)
        args.num_partitions = 1

    scen = process_filename(args.scenario, args.base, args.dir)
    xy = process_filename(args.map, args.base, args.dir)
    with Timer() as t_read:
        reqs = read_scen(scen)

    num_parts = args.num_partitions or 1
    if args.size_partitions:
        num_parts = max(1, -(-len(reqs) // args.size_partitions))
    if args.debug:
        num_parts = 1
    with Timer() as t_workload:
        parts = make_parts(reqs, args, num_parts)

    diffs = args.diffs if args.diffs else ["-"]
    use_fifo = (args.local and os.path.exists(args.fifo)
                and not (args.cutoff and len(reqs) < args.cutoff))
    stats = []
    with Timer() as t_process:
        if use_fifo:
            for diff in diffs:
                stats.append([send_fifo(p, args, diff, args.nfs)
                              for p in parts])
        else:
            engine = LocalEngine(xy, outdir=None, chunk=args.chunk)
            for diff in diffs:
                w_query = (None if diff == "-" else
                           engine.graph.weights_with_diff(read_diff(diff)))
                stats.append([engine.answer(p, args, w_query)
                              for p in parts])

    data = {
        "num_queries": int(len(reqs)),
        "num_partitions": len(parts),
        "t_read": t_read.interval,
        "t_workload": t_workload.interval,
        "t_process": t_process.interval,
    }
    output(data, stats, args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
