"""Batched device A*: the whole query batch searches at once.

TPU-native serving path for the reference's A*-family (``--h-scale
--f-scale``, reference ``args.py:30-57``). The CPU oracle
(``models.astar``) is a faithful heap-based weighted A* — per query,
pointer-chasing, a correctness reference only. This kernel re-expresses the
family the way the CPD build re-expresses Dijkstra (``ops.bellman_ford``):
as a **pruned min-plus fixed-point iteration** over ``[N, Q]`` state, one
dense relaxation per step, fully vectorized over the query batch.

Per iteration, every node ``v`` relaxes over its padded in-edge table::

    g[v, q]  <-  min(g[v, q],  min_k  w[in_eid[v, k]] + prop[in_nbr[v, k], q])

where ``prop`` masks out *pruned* sources: nodes whose
``f = g + h`` exceeds the per-query incumbent ``ub[q] = g[t_q, q]``
(scaled by ``1 + fscale`` when ``fscale > 0``, mirroring the CPU oracle's
incumbent prune). ``h`` is the same heuristic as the CPU oracle —
euclidean distance × ``min_cost_per_unit`` × ``hscale`` — precomputed once
as an ``[N, Q]`` table.

Semantics:

* ``hscale <= 1`` (admissible): pruning only removes nodes that cannot
  improve the incumbent, so converged costs are **exactly optimal** —
  bit-equal to Dijkstra / the CPU oracle (tests pin this).
* ``hscale > 1``: the prune is aggressive, like weighted A*; costs are
  bounded by ``hscale ×`` optimal (the standard weighted-A* bound, asserted
  empirically in tests) but the specific path may differ from the heap
  oracle's, whose result is expansion-order-dependent.
* Telemetry is the **batched analogue** of the heap counters, summed over
  the batch: ``n_expanded`` = propagating nodes that changed last sweep
  (useful frontier work), ``n_surplus`` = propagating nodes re-relaxed
  without having changed (wasted lock-step work — the price of dense
  sweeps), ``n_touched`` = edge relaxations issued, ``n_inserted`` = nodes
  first opened, ``n_updated`` = decrease-key events. Magnitudes differ
  from the heap oracle (a dense sweep re-relaxes whole frontiers); the
  schema and the signals operators read (work per query, wasted work) are
  preserved.

Why in-edges: forward search updates ``g[v]`` from predecessors, which is a
*gather* over the in-edge ELL table — the scatter-free formulation XLA
vectorizes. The batch axis stays minor (``[N, Q]``) so every gather streams
contiguous per-query rows, the same HBM-friendly layout as the build kernel
(``ops.bellman_ford._relax_nb``).

Counters accumulate in float32: a campaign's edge-relaxation count can
exceed int32, and the loss of integer precision past 2^24 is irrelevant for
telemetry.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .device_graph import JINF


@functools.partial(jax.jit, static_argnames=("max_iters",))
def astar_batch(in_nbr: jnp.ndarray, in_eid: jnp.ndarray,
                w_pad: jnp.ndarray, xs: jnp.ndarray, ys: jnp.ndarray,
                s: jnp.ndarray, t: jnp.ndarray,
                hscale: jnp.ndarray, fscale: jnp.ndarray,
                cpu: jnp.ndarray,
                valid: jnp.ndarray | None = None,
                max_iters: int = 0):
    """Batched weighted A* from ``s[q]`` to ``t[q]`` for every query q.

    Parameters
    ----------
    in_nbr, in_eid : int32 [N, K] padded in-edge ELL (self / M for padding)
    w_pad          : int32 [M+1] query-time weights; ``w_pad[M] = INF``
    xs, ys         : float32 [N] node coordinates (heuristic)
    s, t           : int32 [Q]
    hscale, fscale, cpu : scalars (traced — no recompile per knob value);
        ``cpu`` = :func:`models.astar.min_cost_per_unit` for these weights
    valid          : bool [Q] padding mask (False lanes return zeros)
    max_iters      : sweep bound; 0 = N-1 (Bellman-Ford worst case)

    Returns
    -------
    cost [Q] int32, plen [Q] int32, finished [Q] bool,
    counters — dict of float32 scalars (see module docstring)
    """
    n, k = in_nbr.shape
    q = s.shape[0]
    if valid is None:
        valid = jnp.ones((q,), bool)
    limit = (n - 1) if max_iters == 0 else max_iters
    qix = jnp.arange(q)

    # heuristic table [N, Q] ≈ int(hypot * cpu * hscale) (the CPU oracle's
    # h, models/astar.py). Computed in float32 on device, which can round
    # UP past the exact float64 value — an inadmissible-by-one h would
    # break the hscale<=1 optimality guarantee at large coordinate/cost
    # magnitudes — so a conservative margin (4 ulp relative + 1 absolute)
    # keeps h a true lower bound at any scale, at the cost of negligibly
    # weaker pruning.
    dx = xs[:, None] - xs[t][None, :]
    dy = ys[:, None] - ys[t][None, :]
    h_raw = jnp.sqrt(dx * dx + dy * dy) * cpu * hscale
    # clamp below int32 range: a saturating float->int convert is
    # backend-dependent, and a wrapped h would corrupt the prune compare
    h = jnp.maximum(
        jnp.minimum(jnp.floor(h_raw * (1.0 - 4e-7) - 1.0), 2.0e9),
        0.0).astype(jnp.int32)

    g0 = jnp.full((n, q), JINF, jnp.int32).at[s, qix].min(
        jnp.where(valid, jnp.int32(0), JINF))
    hops0 = jnp.zeros((n, q), jnp.int32)
    changed0 = jnp.zeros((n, q), bool).at[s, qix].set(valid)
    zero = jnp.float32(0)
    counters0 = (zero, zero, zero, zero, zero)

    w_in = w_pad[in_eid]                               # [N, K], loop-invariant

    def cond(state):
        i, _, _, changed, _ = state
        return jnp.any(changed) & (i < limit)

    def body(state):
        i, g, hops, changed, (n_exp, n_sur, n_tou, n_ins, n_upd) = state
        ub = g[t, qix]                                  # incumbent per query
        # integer threshold, EXACT at fscale=0 (a float32 compare at
        # ~1e9 magnitudes rounds by up to 64 and could over-prune an
        # optimal-path node, silently breaking hscale<=1 optimality);
        # the fscale>0 threshold is a heuristic bound, so its float
        # rounding is harmless — clamped to JINF to stay in int32
        thr = jnp.where(
            fscale > 0,
            jnp.minimum(jnp.floor((1.0 + fscale)
                                  * ub.astype(jnp.float32)),
                        jnp.float32(JINF)).astype(jnp.int32),
            ub)
        # rearranged compare g > thr - h: exact int32 arithmetic with no
        # wrap (g, thr <= JINF ~1e9; h <= 2e9 keeps thr - h > -2^31)
        pruned = g > (thr[None, :] - h)
        prop = jnp.where(pruned, JINF, g)               # pruned don't push
        via = jnp.minimum(w_in[:, :, None] + prop[in_nbr, :], JINF)
        best = via.min(axis=1)                          # [N, Q]
        slot = via.argmin(axis=1)                       # [N, Q]
        improved = best < g
        hop_src = jnp.take_along_axis(
            hops[in_nbr, :], slot[:, None, :], axis=1)[:, 0, :]
        new_g = jnp.where(improved, best, g)
        new_hops = jnp.where(improved, hop_src + 1, hops)

        live = (prop < JINF) & valid[None, :]           # nodes that pushed
        n_exp = n_exp + (live & changed).sum(dtype=jnp.float32)
        n_sur = n_sur + (live & ~changed).sum(dtype=jnp.float32)
        n_tou = n_tou + live.sum(dtype=jnp.float32) * k
        n_ins = n_ins + (improved & (g >= JINF)).sum(dtype=jnp.float32)
        n_upd = n_upd + (improved & (g < JINF)).sum(dtype=jnp.float32)
        return (i + 1, new_g, new_hops, improved,
                (n_exp, n_sur, n_tou, n_ins, n_upd))

    _, g, hops, _, (n_exp, n_sur, n_tou, n_ins, n_upd) = jax.lax.while_loop(
        cond, body, (jnp.int32(0), g0, hops0, changed0, counters0))

    cost = g[t, qix]
    fin = (cost < JINF) & valid
    cost = jnp.where(fin, cost, 0)
    plen = jnp.where(fin, hops[t, qix], 0)
    counters = dict(n_expanded=n_exp, n_surplus=n_sur, n_touched=n_tou,
                    n_inserted=n_ins, n_updated=n_upd)
    return cost, plen, fin, counters


def astar_batch_np(graph, queries: np.ndarray, w: np.ndarray | None = None,
                   hscale: float = 1.0, fscale: float = 0.0,
                   chunk: int = 1024, deadline: float | None = None,
                   cpu: float | None = None, ctx: dict | None = None,
                   w_key: str | None = None):
    """NumPy-in, NumPy-out convenience wrapper: chunked batched A*.

    Splits ``queries [Q, 2]`` into power-of-two padded chunks of at most
    ``chunk`` (bounding the ``[N, K, Q]`` relaxation working set), checks
    ``deadline`` (``time.perf_counter()`` seconds) **between chunks** — the
    per-batch time budget the reference enforces (reference
    ``args.py:38-57``): remaining chunks are left unfinished, partial
    results returned, like the engine's deadline contract.

    ``cpu`` skips the O(m) ``min_cost_per_unit`` scan when the caller has
    it cached. ``ctx``: a caller-owned dict caching the device-resident
    graph arrays across calls — a resident server (worker/engine.py) must
    not pay graph-sized host→device uploads per request. ``w_key`` names
    the caller's weight set (e.g. the diff file path) so its device copy
    is cached in ``ctx`` too; None uploads the weights per call.

    Returns ``(cost, plen, finished, counters)`` with int64/bool arrays and
    a plain-int counter dict.
    """
    import time as _time

    from ..models.astar import min_cost_per_unit

    nq = len(queries)
    w = graph.w if w is None else np.asarray(w)
    if cpu is None:
        cpu = min_cost_per_unit(graph, w)
    ctx = {} if ctx is None else ctx
    if "in_nbr" not in ctx:
        in_nbr, in_eid = graph.ell("in")
        ctx["in_nbr"] = jnp.asarray(in_nbr, jnp.int32)
        ctx["in_eid"] = jnp.asarray(in_eid, jnp.int32)
        ctx["xs"] = jnp.asarray(graph.xs, jnp.float32)
        ctx["ys"] = jnp.asarray(graph.ys, jnp.float32)
    if w_key is None:
        w_pad = jnp.asarray(graph.padded_weights(w), jnp.int32)
    else:
        wkey = ("w_pad", w_key)
        if wkey not in ctx:
            ctx[wkey] = jnp.asarray(graph.padded_weights(w), jnp.int32)
        w_pad = ctx[wkey]
    in_nbr, in_eid = ctx["in_nbr"], ctx["in_eid"]
    xs, ys = ctx["xs"], ctx["ys"]

    cost = np.zeros(nq, np.int64)
    plen = np.zeros(nq, np.int64)
    fin = np.zeros(nq, bool)
    totals = dict(n_expanded=0, n_surplus=0, n_touched=0, n_inserted=0,
                  n_updated=0)
    for lo in range(0, nq, chunk):
        # always attempt the FIRST chunk: an already-expired budget must
        # still produce a minimal answer, matching the per-query CPU
        # oracle's at-least-one-query behavior (the engine checks its
        # deadline after work, not before)
        if lo > 0 and deadline is not None \
                and _time.perf_counter() > deadline:
            break
        part = queries[lo:lo + chunk]
        m = len(part)
        qpad = 1 << (m - 1).bit_length() if m > 1 else 1
        sq = np.zeros(qpad, np.int32)
        tq = np.zeros(qpad, np.int32)
        vq = np.zeros(qpad, bool)
        sq[:m] = part[:, 0]
        tq[:m] = part[:, 1]
        vq[:m] = True
        c, p, f, counters = astar_batch(
            in_nbr, in_eid, w_pad, xs, ys,
            jnp.asarray(sq), jnp.asarray(tq),
            jnp.float32(hscale), jnp.float32(fscale), jnp.float32(cpu),
            valid=jnp.asarray(vq))
        cost[lo:lo + m] = np.asarray(c[:m], np.int64)
        plen[lo:lo + m] = np.asarray(p[:m], np.int64)
        fin[lo:lo + m] = np.asarray(f[:m], bool)
        for key, val in counters.items():
            totals[key] += int(val)
    return cost, plen, fin, totals
