"""Batched ``table-search``: the query engine.

TPU-native re-expression of the reference's resident query server, which
answers each (s, t) by repeated first-move table lookups, accumulating cost
on the possibly congestion-perturbed graph (``fifo_auto --alg table-search``,
reference ``make_fifos.py:20-22``; hot loop in SURVEY.md §3.3). Instead of a
per-query C++ loop over OpenMP threads, the whole query batch advances in
lock-step: one ``lax.while_loop`` whose body gathers every active query's
next hop at once — answering an entire scenario file in one XLA call
(SURVEY.md §7 stage 4).

Semantics (must match ``models.reference.table_search_walk``):

* moves follow the **free-flow** first-move table; costs accumulate on the
  **query-time** weights (diff applied to ``w_query_pad`` only),
* a query finishes when it reaches its target; it stops unfinished on a
  ``-1`` first move (unreachable) or when the move budget (``k_moves``,
  reference ``args.py:31-36``) runs out,
* ``plen`` = number of edges followed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .device_graph import DeviceGraph


@functools.partial(jax.jit, static_argnames=("max_steps", "unroll"))
def table_search_batch(dg: DeviceGraph, fm: jnp.ndarray,
                       t_rows: jnp.ndarray, s: jnp.ndarray, t: jnp.ndarray,
                       w_query_pad: jnp.ndarray,
                       valid: jnp.ndarray | None = None,
                       k_moves: jnp.ndarray | int = -1,
                       max_steps: int = 0, unroll: int = 8):
    """Answer a batch of queries against a first-move shard.

    Parameters
    ----------
    fm          : int8 [R, N] first-move rows (R = targets owned by this shard)
    t_rows      : int32 [Q] row index of each query's target within ``fm``
    s, t        : int32 [Q] global source / target node ids
    w_query_pad : int32 [M+1] query-time weights (diff applied; last = INF)
    valid       : bool [Q] padding mask (False rows return zeros, unfinished)
    k_moves     : per-batch move budget, -1 = unlimited (reference semantics)
    max_steps   : loop bound; 0 = N (safe upper bound for simple paths)
    unroll      : walk steps per while-loop iteration. Each on-device loop
                  iteration carries a fixed scheduling cost (~0.5 ms
                  measured); batching ``unroll`` gathers per iteration
                  amortizes it. Already-halted lanes re-gather harmlessly
                  (masked), so the only waste is ≤ unroll-1 trailing steps.

    Returns
    -------
    cost [Q] int32, plen [Q] int32, finished [Q] bool
    """
    q = s.shape[0]
    n = dg.n
    limit = n if max_steps == 0 else max_steps
    budget = jnp.where(jnp.asarray(k_moves) < 0, jnp.int32(limit),
                       jnp.asarray(k_moves).astype(jnp.int32))
    if valid is None:
        valid = jnp.ones((q,), jnp.bool_)

    x0 = jnp.where(valid, s.astype(jnp.int32), t.astype(jnp.int32))
    done0 = x0 == t.astype(jnp.int32)
    # cost/plen start from x0 * 0 (not a fresh constant) so that, under
    # shard_map, the carry inherits the inputs' mesh-varying type
    state0 = (
        jnp.int32(0),
        x0,
        x0 * 0,                       # cost
        x0 * 0,                       # plen
        done0,                        # reached target
        done0,                        # halted (reached, stuck, or padding)
    )
    t32 = t.astype(jnp.int32)
    rows32 = t_rows.astype(jnp.int32)

    def cond(state):
        i, _, _, _, _, halted = state
        return (~jnp.all(halted)) & (i < limit)

    # per-batch slot-indexed weight table: W2[x, k] = query-time cost of
    # node x's k-th out-edge. One [N, K] gather up front turns the hot
    # loop's (eid-lookup, weight-lookup) pair into a single gather — the
    # walk is scalar-gather-throughput-bound (~110 M gathered elements/s
    # measured), so gathers per step are the unit of cost.
    w2 = w_query_pad[dg.out_eid]

    def step(x, cost, plen, finished, halted):
        # 2-D gather (row, col) rather than a flattened index: R * N can
        # exceed int32 range on large sharded tables
        slot = fm[rows32, x].astype(jnp.int32)
        can_move = (~halted) & (slot >= 0) & (plen < budget)
        slot_safe = jnp.maximum(slot, 0)
        cost = jnp.where(can_move, cost + w2[x, slot_safe], cost)
        plen = jnp.where(can_move, plen + 1, plen)
        x = jnp.where(can_move, dg.out_nbr[x, slot_safe], x)
        finished = finished | (x == t32)
        halted = halted | finished | ~can_move
        return x, cost, plen, finished, halted

    def body(state):
        i, x, cost, plen, finished, halted = state
        for _ in range(unroll):
            x, cost, plen, finished, halted = step(
                x, cost, plen, finished, halted)
        return i + unroll, x, cost, plen, finished, halted

    _, x, cost, plen, finished, _ = jax.lax.while_loop(cond, body, state0)
    finished = finished & valid
    cost = jnp.where(valid, cost, 0)
    plen = jnp.where(valid, plen, 0)
    return cost, plen, finished


@functools.partial(jax.jit, static_argnames=("k",))
def extract_paths(dg: DeviceGraph, fm: jnp.ndarray, t_rows: jnp.ndarray,
                  s: jnp.ndarray, t: jnp.ndarray, k: int):
    """Materialize the first ``k`` moves of each query's CPD path.

    The reference's prefix extraction (``--k-moves``, reference
    ``args.py:31-36``: "number of moves to extract"): beyond a cost, a
    navigation client wants the next few road segments. One ``lax.scan``
    over ``k`` steps collects the node sequence for the whole batch at
    once.

    Returns ``(nodes, plen)``: int32 ``[Q, k+1]`` node ids — row q starts
    at ``s[q]``; after the path ends (target reached or stuck) the last
    node repeats — and the number of real moves taken (≤ k).
    """
    rows32 = t_rows.astype(jnp.int32)
    t32 = t.astype(jnp.int32)
    x0 = s.astype(jnp.int32)

    def step(x, _):
        slot = fm[rows32, x].astype(jnp.int32)
        can = (slot >= 0) & (x != t32)
        nxt = dg.out_nbr[x, jnp.maximum(slot, 0)]
        x = jnp.where(can, nxt, x)
        return x, (x, can)

    _, (xs, cans) = jax.lax.scan(step, x0, None, length=k)
    nodes = jnp.concatenate([x0[None, :], xs], axis=0).T  # [Q, k+1]
    plen = cans.sum(axis=0).astype(jnp.int32)
    return nodes, plen
