"""Batched ``table-search``: the query engine.

TPU-native re-expression of the reference's resident query server, which
answers each (s, t) by repeated first-move table lookups, accumulating cost
on the possibly congestion-perturbed graph (``fifo_auto --alg table-search``,
reference ``make_fifos.py:20-22``; hot loop in SURVEY.md §3.3). Instead of a
per-query C++ loop over OpenMP threads, the whole query batch advances in
lock-step: one ``lax.while_loop`` whose body gathers every active query's
next hop at once — answering an entire scenario file in one XLA call
(SURVEY.md §7 stage 4).

Semantics (must match ``models.reference.table_search_walk``):

* moves follow the **free-flow** first-move table; costs accumulate on the
  **query-time** weights (diff applied to ``w_query_pad`` only),
* a query finishes when it reaches its target; it stops unfinished on a
  ``-1`` first move (unreachable) or when the move budget (``k_moves``,
  reference ``args.py:31-36``) runs out,
* ``plen`` = number of edges followed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .device_graph import DeviceGraph


#: auto-bucketing: target lanes per bucket / bucket-count cap. ~1k lanes
#: keep the gather pipeline busy on v5e while letting each bucket's
#: while_loop exit at its own max length; 64 buckets bound the per-bucket
#: dispatch overhead (swept end-to-end on the 50k bench across rounds:
#: 64/1024 > 32/2048 > 16/4096 with the lean step — narrower buckets hug
#: the est-sorted length profile, and the per-iteration floor, not lane
#: width, is the binding cost at this size).
#:
#: Round-5 re-sweep (real chip, same 50k bench): 64/unroll=8 113 ms,
#: 32/8 117 ms, 16/8 127 ms, 64/16 117 ms, 64/4 112 ms — the current
#: default stays speed-optimal. NOTE the bench's raw gather-utilization
#: figure moves the OTHER way (16 buckets issue 4.3M lanes at 67 M/s vs
#: 64's 3.5M at 62 M/s): wider buckets pad more wasted lanes which
#: inflate the issued RATE while slowing the actual answer. The knob is
#: tuned for wall-clock, never for that ratio.
BUCKET_LANES = 1024
BUCKET_MAX = 64


def pick_buckets(q: int, n_buckets: int = 0) -> int:
    """Resolve the bucket knob: 0 = auto (≤ ``BUCKET_MAX`` buckets with ≥
    ``BUCKET_LANES`` lanes each). Either way the result is the largest
    divisor of ``q`` not exceeding the requested count, so an awkward
    batch size degrades to the nearest usable split, not to 1."""
    b = min(BUCKET_MAX, max(1, q // BUCKET_LANES)) if n_buckets == 0 \
        else min(max(1, n_buckets), max(q, 1))
    while b > 1 and q % b:
        b -= 1
    return b


def _fm_access(fm: jnp.ndarray, r: int, n: int):
    """``(slot_at, base_of)`` accessors: a flattened 1-D gather per step
    (measured ~7% over the (row, col) 2-D form) when the flat index fits
    int32; the 2-D gather otherwise (large sharded tables)."""
    flat = r * n < (1 << 31)
    fm_flat = fm.reshape(-1) if flat else fm

    def slot_at(rows_b, base, x):
        if flat:
            return fm_flat[base + x].astype(jnp.int32)
        return fm[rows_b, x].astype(jnp.int32)

    def base_of(rows_b):
        return rows_b * n if flat else rows_b

    return slot_at, base_of


def _walk_buckets(step, slot_at, base_of, cost0_of, limit, unroll,
                  n_buckets, rows32, s32, t32, valid):
    """Shared walk scaffold for the single- and multi-diff kernels: one
    ``while_loop`` per bucket under one ``lax.scan``, lean state.

    The walk needs NO per-step arrival check: every fm row holds -1 at
    its own target (``first_move_from_dist`` construction, the
    reference's "no move at the goal"), so arriving lanes halt on the
    stuck test inside ``step`` and ``finished`` is recovered at the end
    as ``x == t``. ``halted0`` derives from the DATA (not a literal) so
    the carry stays mesh-varying under shard_map; pad lanes are halted
    at birth or a mostly-pad tail bucket would walk row 0's full path
    before its while_loop could exit.

    ``step(rows_b, base, x, cost, plen, halted)`` advances one move;
    ``cost0_of(x0)`` shapes the cost carry (``[Q]`` or ``[Q, D]``).
    Returns ``(cost, plen, x == t)`` flattened back to the batch axis.
    """
    def walk_bucket(rows_b, s_b, t_b, valid_b):
        x0 = jnp.where(valid_b, s_b, t_b)
        base = base_of(rows_b)
        halted0 = (slot_at(rows_b, base, x0) < 0) | ~valid_b
        state0 = (jnp.int32(0), x0, cost0_of(x0), x0 * 0, halted0)

        def cond(state):
            i, _, _, _, halted = state
            return (~jnp.all(halted)) & (i < limit)

        def body(state):
            i, x, cost, plen, halted = state
            for _ in range(unroll):
                x, cost, plen, halted = step(rows_b, base, x, cost,
                                             plen, halted)
            return i + unroll, x, cost, plen, halted

        _, x, cost, plen, _ = jax.lax.while_loop(cond, body, state0)
        return cost, plen, x == t_b

    q = s32.shape[0]
    if n_buckets == 1:
        return walk_bucket(rows32, s32, t32, valid)
    qb = q // n_buckets

    def scan_body(carry, args):
        return carry, walk_bucket(*args)

    _, outs = jax.lax.scan(
        scan_body, jnp.int32(0),
        tuple(a.reshape(n_buckets, qb)
              for a in (rows32, s32, t32, valid)))
    return jax.tree.map(lambda o: o.reshape(q, *o.shape[2:]), outs)


@functools.partial(jax.jit,
                   static_argnames=("k_moves", "max_steps", "unroll",
                                    "n_buckets"))
def table_search_batch(dg: DeviceGraph, fm: jnp.ndarray,
                       t_rows: jnp.ndarray, s: jnp.ndarray, t: jnp.ndarray,
                       w_query_pad: jnp.ndarray,
                       valid: jnp.ndarray | None = None,
                       k_moves: int = -1,
                       max_steps: int = 0, unroll: int = 8,
                       n_buckets: int = 0):
    """Answer a batch of queries against a first-move shard.

    Parameters
    ----------
    fm          : int8 [R, N] first-move rows (R = targets owned by this shard)
    t_rows      : int32 [Q] row index of each query's target within ``fm``
    s, t        : int32 [Q] global source / target node ids
    w_query_pad : int32 [M+1] query-time weights (diff applied; last = INF)
    valid       : bool [Q] padding mask (False rows return zeros, unfinished)
    k_moves     : per-batch move budget, -1 = unlimited (reference semantics)
    max_steps   : loop bound; 0 = N (safe upper bound for simple paths)
    unroll      : walk steps per while-loop iteration. Each on-device loop
                  iteration carries a fixed scheduling cost (~0.5 ms
                  measured); batching ``unroll`` gathers per iteration
                  amortizes it. Already-halted lanes re-gather harmlessly
                  (masked), so the only waste is ≤ unroll-1 trailing steps.
    n_buckets   : split the batch into equal contiguous buckets, each with
                  its OWN while_loop (one ``lax.scan`` — a single XLA
                  call). A lock-step walk runs the whole batch for
                  max-plen steps; with callers sorting queries by expected
                  length (``CPDOracle.route`` sorts by coordinate
                  distance), each bucket exits at its own max — 3.9x
                  measured on the 50k-query bench. 0 = auto
                  (:func:`pick_buckets`); 1 = single lock-step batch.
                  Results are bucket-invariant either way.

    Returns
    -------
    cost [Q] int32, plen [Q] int32, finished [Q] bool
    """
    q = s.shape[0]
    n = dg.n
    r = fm.shape[0]
    limit = n if max_steps == 0 else max_steps
    # static specialization: k_moves is a STATIC argname (its values are
    # -1 or a per-campaign constant, so recompiles are bounded), which
    # makes this a trace-time Python bool — for the common serving call
    # (-1 unlimited, the reference default, max_steps=0) the per-step
    # budget compare vanishes from the compiled program entirely (safe:
    # a CPD walk follows a simple path, so it reaches its target or a
    # -1 slot in < N moves; only an explicit truncation needs the exact
    # per-step plen cap)
    k_moves = int(k_moves)
    unlimited = k_moves < 0 and max_steps == 0
    if not unlimited:
        budget = jnp.int32(limit if k_moves < 0 else k_moves)
    if valid is None:
        valid = jnp.ones((q,), jnp.bool_)
    n_buckets = pick_buckets(q, n_buckets)

    t32 = t.astype(jnp.int32)
    rows32 = t_rows.astype(jnp.int32)

    # packed (next-node, weight) table: pair[x, k] = node x's k-th
    # out-edge as two adjacent int32s. The walk is scalar-gather-
    # throughput-bound, so gathers per step are the unit of cost; one
    # contiguous 8-byte gather replaces the separate weight and
    # next-node gathers — 3 gathers/step -> 2, measured 1.5x on the
    # bench walk. Built once per call (one [N, K] pass, trivial vs the
    # walk).
    pair = jnp.stack([dg.out_nbr.astype(jnp.int32),
                      w_query_pad[dg.out_eid]], axis=-1)

    slot_at, base_of = _fm_access(fm, r, n)

    # lean step: 2 gathers + 1 compare + 4 selects (the budget compare
    # only exists when not `unlimited`); see _walk_buckets for why no
    # per-step arrival check is needed
    def step(rows_b, base, x, cost, plen, halted):
        slot = slot_at(rows_b, base, x)
        can_move = (~halted) & (slot >= 0)
        if not unlimited:
            can_move &= plen < budget
        nxt_w = pair[x, jnp.maximum(slot, 0)]   # [Q, 2] one gather
        cost = jnp.where(can_move, cost + nxt_w[:, 1], cost)
        plen = jnp.where(can_move, plen + 1, plen)
        x = jnp.where(can_move, nxt_w[:, 0], x)
        halted = halted | ~can_move
        return x, cost, plen, halted

    cost, plen, finished = _walk_buckets(
        step, slot_at, base_of, lambda x0: x0 * 0, limit, unroll,
        n_buckets, rows32, s.astype(jnp.int32), t32, valid)
    finished = finished & valid
    cost = jnp.where(valid, cost, 0)
    plen = jnp.where(valid, plen, 0)
    return cost, plen, finished


@functools.partial(jax.jit,
                   static_argnames=("max_steps", "unroll", "n_buckets"))
def table_search_multi(dg: DeviceGraph, fm: jnp.ndarray,
                       t_rows: jnp.ndarray, s: jnp.ndarray, t: jnp.ndarray,
                       w_pads: jnp.ndarray,
                       valid: jnp.ndarray | None = None,
                       max_steps: int = 0, unroll: int = 8,
                       n_buckets: int = 0):
    """Answer a batch under D congestion diffs in ONE fused walk.

    The reference campaign serves one round per diff file, re-walking
    every query each round (reference ``process_query.py:178``). But a
    table-search trajectory is **diff-independent** — moves follow the
    free-flow first-move table; only cost accumulation sees the
    query-time weights (reference semantics, this module's header). So
    one walk can accumulate all D diffs' costs at once: per step, one
    packed (next-node, edge-id) gather drives the move and one ``[D]``
    row gather from the transposed weight matrix accumulates every
    diff's cost — ~3 gathers/step total instead of 2 PER DIFF for D
    sequential rounds (≈ 2D/3 fewer gathers, bounded by the D-wide
    row-gather's bandwidth).

    Parameters as :func:`table_search_batch` except ``w_pads``: int32
    ``[D, M+1]`` — one padded weight row per diff (row d =
    ``graph.padded_weights(w_diff_d)``; include free flow as a row to
    get it fused too). There is no ``k_moves``: the fused path serves
    the unlimited reference default; budgeted campaigns fall back to
    sequential rounds (``cli.process_query``). ``max_steps`` truncates
    exactly like the single-diff kernel's.

    Returns ``(cost [D, Q], plen [Q], finished [Q])`` — plen/finished
    are shared across diffs because the trajectory is.
    """
    q = s.shape[0]
    n = dg.n
    r = fm.shape[0]
    limit = n if max_steps == 0 else max_steps
    if valid is None:
        valid = jnp.ones((q,), jnp.bool_)
    n_buckets = pick_buckets(q, n_buckets)
    d = w_pads.shape[0]

    t32 = t.astype(jnp.int32)
    rows32 = t_rows.astype(jnp.int32)

    # packed (next-node, edge-id) pair + [M+1, D] transposed weights:
    # the per-step [Q, D] weight gather reads D contiguous int32s per
    # lane, the same widening trick as the single-diff (next, w) pair
    pair = jnp.stack([dg.out_nbr.astype(jnp.int32),
                      dg.out_eid.astype(jnp.int32)], axis=-1)
    w_t = w_pads.T                                   # [M+1, D]

    slot_at, base_of = _fm_access(fm, r, n)

    # mirror table_search_batch's truncation contract: an explicit
    # max_steps caps plen EXACTLY per step (the while cond alone would
    # overshoot by up to unroll-1 moves)
    bounded = max_steps != 0

    def step(rows_b, base, x, cost, plen, halted):
        slot = slot_at(rows_b, base, x)
        can_move = (~halted) & (slot >= 0)
        if bounded:
            can_move &= plen < limit
        nxt_eid = pair[x, jnp.maximum(slot, 0)]  # [Q, 2]
        w_row = w_t[nxt_eid[:, 1]]               # [Q, D] one gather
        cost = jnp.where(can_move[:, None], cost + w_row, cost)
        plen = jnp.where(can_move, plen + 1, plen)
        x = jnp.where(can_move, nxt_eid[:, 0], x)
        halted = halted | ~can_move
        return x, cost, plen, halted

    def cost0_of(x0):
        return (jnp.zeros((x0.shape[0], d), jnp.int32)
                + (x0 * 0)[:, None])

    cost, plen, finished = _walk_buckets(
        step, slot_at, base_of, cost0_of, limit, unroll,
        n_buckets, rows32, s.astype(jnp.int32), t32, valid)
    finished = finished & valid
    cost = jnp.where(valid[:, None], cost, 0).T      # [D, Q]
    plen = jnp.where(valid, plen, 0)
    return cost, plen, finished


@functools.partial(jax.jit, static_argnames=("k",))
def extract_paths(dg: DeviceGraph, fm: jnp.ndarray, t_rows: jnp.ndarray,
                  s: jnp.ndarray, t: jnp.ndarray, k: int):
    """Materialize the first ``k`` moves of each query's CPD path.

    The reference's prefix extraction (``--k-moves``, reference
    ``args.py:31-36``: "number of moves to extract"): beyond a cost, a
    navigation client wants the next few road segments. One ``lax.scan``
    over ``k`` steps collects the node sequence for the whole batch at
    once.

    Returns ``(nodes, plen)``: int32 ``[Q, k+1]`` node ids — row q starts
    at ``s[q]``; after the path ends (target reached or stuck) the last
    node repeats — and the number of real moves taken (≤ k).
    """
    rows32 = t_rows.astype(jnp.int32)
    t32 = t.astype(jnp.int32)
    x0 = s.astype(jnp.int32)

    def step(x, _):
        slot = fm[rows32, x].astype(jnp.int32)
        can = (slot >= 0) & (x != t32)
        nxt = dg.out_nbr[x, jnp.maximum(slot, 0)]
        x = jnp.where(can, nxt, x)
        return x, (x, can)

    _, (xs, cans) = jax.lax.scan(step, x0, None, length=k)
    nodes = jnp.concatenate([x0[None, :], xs], axis=0).T  # [Q, k+1]
    plen = cans.sum(axis=0).astype(jnp.int32)
    return nodes, plen
