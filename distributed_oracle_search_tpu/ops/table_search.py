"""Batched ``table-search``: the query engine.

TPU-native re-expression of the reference's resident query server, which
answers each (s, t) by repeated first-move table lookups, accumulating cost
on the possibly congestion-perturbed graph (``fifo_auto --alg table-search``,
reference ``make_fifos.py:20-22``; hot loop in SURVEY.md §3.3). Instead of a
per-query C++ loop over OpenMP threads, the whole query batch advances in
lock-step: one ``lax.while_loop`` whose body gathers every active query's
next hop at once — answering an entire scenario file in one XLA call
(SURVEY.md §7 stage 4).

Semantics (must match ``models.reference.table_search_walk``):

* moves follow the **free-flow** first-move table; costs accumulate on the
  **query-time** weights (diff applied to ``w_query_pad`` only),
* a query finishes when it reaches its target; it stops unfinished on a
  ``-1`` first move (unreachable) or when the move budget (``k_moves``,
  reference ``args.py:31-36``) runs out,
* ``plen`` = number of edges followed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .device_graph import DeviceGraph


#: auto-bucketing: target lanes per bucket / bucket-count cap. ~1k lanes
#: keep the gather pipeline busy on v5e while letting each bucket's
#: while_loop exit at its own max length; 64 buckets bound the per-bucket
#: dispatch overhead (swept end-to-end on the 50k bench across rounds:
#: 64/1024 > 32/2048 > 16/4096 with the lean step — narrower buckets hug
#: the est-sorted length profile, and the per-iteration floor, not lane
#: width, is the binding cost at this size)
BUCKET_LANES = 1024
BUCKET_MAX = 64


def pick_buckets(q: int, n_buckets: int = 0) -> int:
    """Resolve the bucket knob: 0 = auto (≤ ``BUCKET_MAX`` buckets with ≥
    ``BUCKET_LANES`` lanes each). Either way the result is the largest
    divisor of ``q`` not exceeding the requested count, so an awkward
    batch size degrades to the nearest usable split, not to 1."""
    b = min(BUCKET_MAX, max(1, q // BUCKET_LANES)) if n_buckets == 0 \
        else min(max(1, n_buckets), max(q, 1))
    while b > 1 and q % b:
        b -= 1
    return b


@functools.partial(jax.jit,
                   static_argnames=("max_steps", "unroll", "n_buckets"))
def table_search_batch(dg: DeviceGraph, fm: jnp.ndarray,
                       t_rows: jnp.ndarray, s: jnp.ndarray, t: jnp.ndarray,
                       w_query_pad: jnp.ndarray,
                       valid: jnp.ndarray | None = None,
                       k_moves: jnp.ndarray | int = -1,
                       max_steps: int = 0, unroll: int = 8,
                       n_buckets: int = 0):
    """Answer a batch of queries against a first-move shard.

    Parameters
    ----------
    fm          : int8 [R, N] first-move rows (R = targets owned by this shard)
    t_rows      : int32 [Q] row index of each query's target within ``fm``
    s, t        : int32 [Q] global source / target node ids
    w_query_pad : int32 [M+1] query-time weights (diff applied; last = INF)
    valid       : bool [Q] padding mask (False rows return zeros, unfinished)
    k_moves     : per-batch move budget, -1 = unlimited (reference semantics)
    max_steps   : loop bound; 0 = N (safe upper bound for simple paths)
    unroll      : walk steps per while-loop iteration. Each on-device loop
                  iteration carries a fixed scheduling cost (~0.5 ms
                  measured); batching ``unroll`` gathers per iteration
                  amortizes it. Already-halted lanes re-gather harmlessly
                  (masked), so the only waste is ≤ unroll-1 trailing steps.
    n_buckets   : split the batch into equal contiguous buckets, each with
                  its OWN while_loop (one ``lax.scan`` — a single XLA
                  call). A lock-step walk runs the whole batch for
                  max-plen steps; with callers sorting queries by expected
                  length (``CPDOracle.route`` sorts by coordinate
                  distance), each bucket exits at its own max — 3.9x
                  measured on the 50k-query bench. 0 = auto
                  (:func:`pick_buckets`); 1 = single lock-step batch.
                  Results are bucket-invariant either way.

    Returns
    -------
    cost [Q] int32, plen [Q] int32, finished [Q] bool
    """
    q = s.shape[0]
    n = dg.n
    r = fm.shape[0]
    limit = n if max_steps == 0 else max_steps
    # static specialization: the common serving call passes the Python
    # literal -1 (unlimited, the reference default) with max_steps=0 —
    # then the per-step budget compare vanishes from the compiled
    # program entirely (safe: a CPD walk follows a simple path, so it
    # reaches its target or a -1 slot in < N moves; only an explicit
    # max_steps truncation needs the exact per-step plen cap)
    unlimited = (isinstance(k_moves, int) and k_moves < 0
                 and max_steps == 0)
    if not unlimited:
        budget = jnp.where(jnp.asarray(k_moves) < 0, jnp.int32(limit),
                           jnp.asarray(k_moves).astype(jnp.int32))
    if valid is None:
        valid = jnp.ones((q,), jnp.bool_)
    n_buckets = pick_buckets(q, n_buckets)

    t32 = t.astype(jnp.int32)
    rows32 = t_rows.astype(jnp.int32)

    # packed (next-node, weight) table: pair[x, k] = node x's k-th
    # out-edge as two adjacent int32s. The walk is scalar-gather-
    # throughput-bound, so gathers per step are the unit of cost; one
    # contiguous 8-byte gather replaces the separate weight and
    # next-node gathers — 3 gathers/step -> 2, measured 1.5x on the
    # bench walk. Built once per call (one [N, K] pass, trivial vs the
    # walk).
    pair = jnp.stack([dg.out_nbr.astype(jnp.int32),
                      w_query_pad[dg.out_eid]], axis=-1)

    # flattened fm for a 1-D gather per step (measured ~7% over the
    # (row, col) 2-D form); falls back to 2-D when R * N would overflow
    # the int32 flat index (large sharded tables)
    flat = r * n < (1 << 31)
    fm_flat = fm.reshape(-1) if flat else fm

    def slot_at(rows_b, base, x):
        if flat:
            return fm_flat[base + x].astype(jnp.int32)
        return fm[rows_b, x].astype(jnp.int32)

    def walk_bucket(rows_b, s_b, t_b, valid_b):
        x0 = jnp.where(valid_b, s_b, t_b)
        base = rows_b * n if flat else rows_b
        # the walk needs NO per-step arrival check: every fm row holds
        # -1 at its own target (first_move_from_dist construction, the
        # reference's "no move at the goal"), so arriving lanes halt on
        # the stuck test and `finished` is recovered at the end as
        # x == t. Dropping the finished carry and (when `unlimited`)
        # the budget compare leaves 2 gathers + 1 compare + 4 selects
        # per step. halted0 derives from the DATA (not a literal) so
        # the carry stays mesh-varying under shard_map; pad lanes are
        # halted at birth or a mostly-pad tail bucket would walk row
        # 0's full path before its while_loop could exit
        halted0 = (slot_at(rows_b, base, x0) < 0) | ~valid_b
        state0 = (jnp.int32(0), x0, x0 * 0, x0 * 0, halted0)

        def cond(state):
            i, _, _, _, halted = state
            return (~jnp.all(halted)) & (i < limit)

        def step(x, cost, plen, halted):
            slot = slot_at(rows_b, base, x)
            can_move = (~halted) & (slot >= 0)
            if not unlimited:
                can_move &= plen < budget
            slot_safe = jnp.maximum(slot, 0)
            nxt_w = pair[x, slot_safe]          # [Q, 2] one gather
            cost = jnp.where(can_move, cost + nxt_w[:, 1], cost)
            plen = jnp.where(can_move, plen + 1, plen)
            x = jnp.where(can_move, nxt_w[:, 0], x)
            halted = halted | ~can_move
            return x, cost, plen, halted

        def body(state):
            i, x, cost, plen, halted = state
            for _ in range(unroll):
                x, cost, plen, halted = step(x, cost, plen, halted)
            return i + unroll, x, cost, plen, halted

        _, x, cost, plen, _ = jax.lax.while_loop(cond, body, state0)
        return cost, plen, x == t_b

    if n_buckets == 1:
        cost, plen, finished = walk_bucket(rows32, s.astype(jnp.int32),
                                           t32, valid)
    else:
        qb = q // n_buckets

        def scan_body(carry, args):
            return carry, walk_bucket(*args)

        _, (cost, plen, finished) = jax.lax.scan(
            scan_body, jnp.int32(0),
            (rows32.reshape(n_buckets, qb),
             s.astype(jnp.int32).reshape(n_buckets, qb),
             t32.reshape(n_buckets, qb),
             valid.reshape(n_buckets, qb)))
        cost = cost.reshape(q)
        plen = plen.reshape(q)
        finished = finished.reshape(q)
    finished = finished & valid
    cost = jnp.where(valid, cost, 0)
    plen = jnp.where(valid, plen, 0)
    return cost, plen, finished


@functools.partial(jax.jit, static_argnames=("k",))
def extract_paths(dg: DeviceGraph, fm: jnp.ndarray, t_rows: jnp.ndarray,
                  s: jnp.ndarray, t: jnp.ndarray, k: int):
    """Materialize the first ``k`` moves of each query's CPD path.

    The reference's prefix extraction (``--k-moves``, reference
    ``args.py:31-36``: "number of moves to extract"): beyond a cost, a
    navigation client wants the next few road segments. One ``lax.scan``
    over ``k`` steps collects the node sequence for the whole batch at
    once.

    Returns ``(nodes, plen)``: int32 ``[Q, k+1]`` node ids — row q starts
    at ``s[q]``; after the path ends (target reached or stuck) the last
    node repeats — and the number of real moves taken (≤ k).
    """
    rows32 = t_rows.astype(jnp.int32)
    t32 = t.astype(jnp.int32)
    x0 = s.astype(jnp.int32)

    def step(x, _):
        slot = fm[rows32, x].astype(jnp.int32)
        can = (slot >= 0) & (x != t32)
        nxt = dg.out_nbr[x, jnp.maximum(slot, 0)]
        x = jnp.where(can, nxt, x)
        return x, (x, can)

    _, (xs, cans) = jax.lax.scan(step, x0, None, length=k)
    nodes = jnp.concatenate([x0[None, :], xs], axis=0).T  # [Q, k+1]
    plen = cans.sum(axis=0).astype(jnp.int32)
    return nodes, plen
