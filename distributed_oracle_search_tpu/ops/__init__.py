from .device_graph import DeviceGraph
from .bellman_ford import dist_to_targets, first_move_from_dist, build_fm_columns
from .table_search import table_search_batch

__all__ = [
    "DeviceGraph", "dist_to_targets", "first_move_from_dist",
    "build_fm_columns", "table_search_batch",
]
