from .device_graph import DeviceGraph
from .bellman_ford import dist_to_targets, first_move_from_dist, build_fm_columns
from .table_search import extract_paths, table_search_batch
from .pallas_walk import (
    choose_walk_kernel, pallas_walk_batch, pallas_walk_fits,
    resolve_walk_kernel,
)
from .pointer_doubling import doubled_tables, lookup_tables
from .shift_relax import ShiftGraph, dist_to_targets_shift
from .batched_astar import astar_batch, astar_batch_np

__all__ = [
    "DeviceGraph", "dist_to_targets", "first_move_from_dist",
    "build_fm_columns", "table_search_batch", "extract_paths",
    "choose_walk_kernel", "pallas_walk_batch", "pallas_walk_fits",
    "resolve_walk_kernel",
    "doubled_tables", "lookup_tables", "ShiftGraph",
    "dist_to_targets_shift", "astar_batch", "astar_batch_np",
]
