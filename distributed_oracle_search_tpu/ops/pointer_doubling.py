"""Pointer-doubling: whole-shard path costs in O(log L) sweeps.

The framework's "long-context" machinery (SURVEY.md §5): a table-search
walk is a sequential chain of up to L = max-path-length dependent gathers —
the structural analog of a long sequence. Instead of walking each query,
**double the successor function**: with

    S_0[r, x] = next node on the CPD path from x toward target r
    C_0[r, x] = query-time cost of that one move

repeated squaring

    S_{k+1}[r, x] = S_k[r, S_k[r, x]]
    C_{k+1}[r, x] = C_k[r, x] + C_k[r, S_k[r, x]]

converges in ceil(log2 L) sweeps to the TOTAL cost from every node to
every owned target — after which any (s, t) query is ONE gather, on diffed
weights too (the walk's only advantage was laziness).

Cost model — MEASURED, not aspirational, and regenerated every bench run
(bench graph 9216x9216, v5e, captured in the driver's BENCH artifacts —
the ``table_breakeven_queries`` field is computed from the same run's
prepare/walk/lookup timings, never quoted from memory): one sweep is ONE
packed dependent ``[R, N]`` gather (succ, cost, plen as 12 adjacent
bytes) — ~**19 s** prepare for the full shard, then lookups at ~320-520k
q/s vs the ~200-310k q/s diffed walk (r04 captures; the tunneled link
swings individual runs ±20%). Break-even
(``prepare / (1/walk_qps − 1/lookup_qps)``) divides by the small
walk-vs-lookup gap, so captures range ~**9-34M queries** per diff round
before the tables pay for themselves — every point in that band is the
regime of BASELINE.md configs[4]'s 10M-query DIMACS campaign, not of
small scenarios. ``doubled_tables_multi`` changes the arithmetic
D-fold: the fused sweep prepares D diffs' tables for ~one prepare
(measured 4 diffs in 16.5 s vs 18.8 s for one — the sweep is
lane-bound, not byte-bound), dividing the per-diff break-even by ~D. Memory:
cost int32 + sign-packed plen (int16 when ``N < 32768``) = 6-8 bytes per
entry = **6-8x the fm shard**; ``models.cpd.prepare_weights`` enforces a
budget gate before allocating.
Self-loops make the recursion total: the target itself and stuck
(unreachable) nodes point at themselves with step cost 0, so their
accumulated cost is exactly the walk's cost-until-stuck.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .device_graph import DeviceGraph


def plen_dtype(n: int):
    """Packed-plen dtype: int16 when every path length (< N) fits with
    the sign bit spare, else int32."""
    return jnp.int16 if n < (1 << 15) else jnp.int32


@functools.partial(jax.jit, static_argnames=("max_len",))
def doubled_tables(dg: DeviceGraph, fm: jnp.ndarray, targets: jnp.ndarray,
                   w_query_pad: jnp.ndarray, max_len: int = 0):
    """All-source cost + packed-plen tables for one fm shard.

    Parameters
    ----------
    fm          : int8 [R, N] first-move rows (free-flow moves)
    targets     : int32 [R] global node id of each row's target (-1 pad)
    w_query_pad : int32 [M+1] query-time weights (diff applied)
    max_len     : path-length bound (0 = N, the simple-path bound)

    Returns
    -------
    cost [R, N] int32, plen_packed [R, N] (:func:`plen_dtype`):
    ``finished`` rides plen's sign — finished entries store ``plen``,
    unfinished store ``-plen - 1`` (decode via :func:`lookup_tables`).
    Rows with ``targets[r] < 0`` are all-unfinished padding. Dropping the
    separate finished tensor and narrowing plen cuts the table from 12 to
    6-8 bytes per entry.
    """
    r, n = fm.shape
    limit = n if max_len == 0 else max_len
    rows = jnp.arange(r, dtype=jnp.int32)[:, None]
    x = jnp.arange(n, dtype=jnp.int32)[None, :]

    slot = fm.astype(jnp.int32)
    can = slot >= 0
    slot_safe = jnp.maximum(slot, 0)
    eid = dg.out_eid[x.repeat(r, 0), slot_safe]
    nxt = dg.out_nbr[x.repeat(r, 0), slot_safe]
    succ = jnp.where(can, nxt, x)                  # self-loop when stuck
    cost = jnp.where(can, w_query_pad[eid], 0)
    plen = jnp.where(can, 1, 0).astype(jnp.int32)

    n_sweeps = max(int(limit - 1).bit_length(), 1)

    def cond(state):
        i, _, _, _, changed = state
        return changed & (i < n_sweeps)

    def body(state):
        i, succ, cost, plen, _ = state
        # (succ, cost, plen) share the gather indices: pack them as three
        # adjacent int32s so ONE take_along_axis (12 contiguous bytes per
        # lane) replaces three separate gathers — measured 2.1x on the
        # bench shard's prepare
        packed = jnp.stack([succ, cost, plen], axis=-1)
        gat = jnp.take_along_axis(packed, succ[..., None], axis=1)
        new_succ = gat[..., 0]
        cost = cost + gat[..., 1]
        plen = plen + gat[..., 2]
        # converged once every chain reached its fixed point: the sweep
        # count then adapts to log2(actual max path length), not log2(N)
        return i + 1, new_succ, cost, plen, jnp.any(new_succ != succ)

    # Seed `changed` from the data (True iff some chain is not yet at its
    # fixed point) rather than the literal True: under shard_map the body's
    # jnp.any(...) output is varying over the worker axis, so the initial
    # carry must be varying too or tracing rejects the loop.
    changed0 = jnp.any(succ != x)
    _, succ, cost, plen, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), succ, cost, plen, changed0))

    valid = targets >= 0
    t_safe = jnp.where(valid, targets, 0).astype(jnp.int32)
    finished = (succ == t_safe[:, None]) & valid[:, None]
    del rows
    plen_packed = jnp.where(finished, plen, -plen - 1).astype(plen_dtype(n))
    return cost, plen_packed


@functools.partial(jax.jit, static_argnames=("max_len",))
def doubled_tables_multi(dg: DeviceGraph, fm: jnp.ndarray,
                         targets: jnp.ndarray, w_pads: jnp.ndarray,
                         max_len: int = 0):
    """All-source cost tables for one fm shard under D diffs at once.

    The successor function is diff-independent (free-flow moves), so
    the doubling recursion is shared: one fused sweep squares ``succ``
    and accumulates EVERY diff's costs with a single
    ``jnp.take_along_axis`` of ``(2 + D)`` adjacent int32s per lane —
    preparing D diff rounds' tables for ~the price of one (the sweep is
    gather-bound; only the payload widens). ``w_pads``: int32
    ``[D, M+1]``, one padded weight row per diff.

    Returns ``(costs [R, N, D] int32, plen_packed [R, N])`` —
    ``plen``/``finished`` ride one shared sign-packed array because the
    trajectory is shared (:func:`doubled_tables` packing). The costs
    layout keeps D innermost so a serving lookup reads one query's D
    costs as one contiguous ``[D]``-wide gather
    (:func:`lookup_tables_multi`).
    """
    r, n = fm.shape
    d = w_pads.shape[0]
    limit = n if max_len == 0 else max_len
    x = jnp.arange(n, dtype=jnp.int32)[None, :]

    slot = fm.astype(jnp.int32)
    can = slot >= 0
    slot_safe = jnp.maximum(slot, 0)
    eid = dg.out_eid[x.repeat(r, 0), slot_safe]
    nxt = dg.out_nbr[x.repeat(r, 0), slot_safe]
    succ = jnp.where(can, nxt, x)                  # self-loop when stuck
    costs = jnp.where(can[..., None], w_pads.T[eid], 0)      # [R, N, D]
    plen = jnp.where(can, 1, 0).astype(jnp.int32)

    n_sweeps = max(int(limit - 1).bit_length(), 1)

    def cond(state):
        i, _, _, _, changed = state
        return changed & (i < n_sweeps)

    def body(state):
        i, succ, costs, plen, _ = state
        packed = jnp.concatenate(
            [succ[..., None], plen[..., None], costs], axis=-1)
        gat = jnp.take_along_axis(packed, succ[..., None], axis=1)
        new_succ = gat[..., 0]
        plen = plen + gat[..., 1]
        costs = costs + gat[..., 2:]
        return i + 1, new_succ, costs, plen, jnp.any(new_succ != succ)

    changed0 = jnp.any(succ != x)
    _, succ, costs, plen, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), succ, costs, plen, changed0))

    valid = targets >= 0
    t_safe = jnp.where(valid, targets, 0).astype(jnp.int32)
    finished = (succ == t_safe[:, None]) & valid[:, None]
    plen_packed = jnp.where(finished, plen, -plen - 1).astype(plen_dtype(n))
    return costs, plen_packed


@jax.jit
def lookup_tables_multi(costs: jnp.ndarray, plen_packed: jnp.ndarray,
                        t_rows: jnp.ndarray, s: jnp.ndarray,
                        valid: jnp.ndarray | None = None):
    """Answer queries from fused multi-diff tables: one contiguous
    ``[D]``-wide gather per query plus the shared plen gather.

    Returns ``(cost [D, Q], plen [Q], finished [Q])``.
    """
    rows = t_rows.astype(jnp.int32)
    s32 = s.astype(jnp.int32)
    cost_qd = costs[rows, s32]                     # [Q, D] one gather
    pp = plen_packed[rows, s32].astype(jnp.int32)
    f = pp >= 0
    p = jnp.where(f, pp, -pp - 1)
    if valid is not None:                   # same masking contract as
        cost_qd = jnp.where(valid[:, None], cost_qd, 0)  # lookup_tables
        p = jnp.where(valid, p, 0)
        f = f & valid
    return cost_qd.T, p, f


def unpack_tables(cost, plen_packed):
    """Whole-table decode (cost, plen, finished) — for tests and direct
    table consumers; serving uses :func:`lookup_tables` per query."""
    pp = plen_packed.astype(jnp.int32)
    f = pp >= 0
    return cost, jnp.where(f, pp, -pp - 1), f


@jax.jit
def lookup_tables(cost: jnp.ndarray, plen_packed: jnp.ndarray,
                  t_rows: jnp.ndarray, s: jnp.ndarray,
                  valid: jnp.ndarray | None = None):
    """Answer queries from prepared tables: one 2-D gather each.

    Decodes the sign-packed plen: ``finished = packed >= 0``,
    ``plen = packed`` when finished else ``-packed - 1``.
    """
    rows = t_rows.astype(jnp.int32)
    s32 = s.astype(jnp.int32)
    c = cost[rows, s32]
    pp = plen_packed[rows, s32].astype(jnp.int32)
    f = pp >= 0
    p = jnp.where(f, pp, -pp - 1)
    if valid is not None:
        c = jnp.where(valid, c, 0)
        p = jnp.where(valid, p, 0)
        f = f & valid
    return c, p, f
