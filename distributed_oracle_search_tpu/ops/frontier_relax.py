"""Delta-stepping frontier relaxation: the build kernel for high-diameter
irregular graphs (road networks).

The dense kernels (``bellman_ford``, ``ell_split``) sweep ALL N nodes
every iteration; iteration count ~ the max shortest-path hop length
(~graph diameter D). Road networks are the worst case for that product:
N large, D large (hundreds), frontiers tiny — a 264k-node network pays
D x N x K row-gathers while a CPU Dijkstra pays ~E log N per target
(the reference builds exactly that way: one Dijkstra per owned node
under OpenMP, reference ``README.md:88-95``). Round 3's bench measured
the dense split kernel at 0.65x ONE CPU core on that family; the dense
sweep simply does ~D x more relaxation work than the frontier carries.

This kernel keeps the relaxation *sparse* without leaving XLA's static
shapes — a device-resident **priority work queue** over nodes:

* ``prio`` int32 [N] — INF = idle; otherwise the node's wake priority:
  the smallest just-improved distance among its out-neighbors (a lower
  bound on the improvement it can still receive). Exactly Dijkstra's
  queue discipline, batched and approximate.
* Each iteration pops every node with ``prio <= min(prio) + delta``
  (delta-stepping's bucket, one compare + ``jnp.nonzero(size=F)`` —
  static shape, one compile), gathers ONLY those rows' out-edges
  ``[F, K]``, relaxes all B target columns at once ``[F, K, B]``,
  scatter-mins into the distance table, and scatter-mins the improved
  rows' new minima into their in-neighbors' ``prio`` (``[F, K_in]``).
  ``s_unroll`` relax sub-steps run per pop so chains inside one bucket
  settle without re-popping (measured 2x fewer iterations at S=2).
* Pad slots write index n -> dropped by scatter semantics; gathers clip
  to row n-1, whose redundant relaxation is masked out of the wake set.
  Queue overflow (> F ready) just leaves the rest armed: cleared bits
  are only the popped F, so the bucket drains over iterations —
  correctness never depends on F or delta (any pop order converges to
  the same unique fixed point; delta only controls how Dijkstra-like,
  and therefore how small, the re-expansion count is).

Why pop by distance and not FIFO: the graph's weight spread (highway
links ~500x a street block) makes hop order diverge from distance
order, and FIFO label-correcting re-expands whole subtrees each time a
shorter path lands — measured 8,870 pops vs 799 for delta-stepping on
the same 264k road graph.

Measured per-iteration cost on v5e-via-tunnel is ~0.3 ms floor plus
~25-50 ns per gathered row, nearly independent of the row payload up
to ~1 KB — so the batch axis B is almost free while iterations are
expensive. The production defaults (F=2048, delta~32 x mean weight,
S=2, B=512; every deviation swept worse) build the 264k road graph at
23-41 rows/s across r04 captures (2.7-4.3x one CPU core's Dijkstra,
device-window dependent) and ~80-150 rows/s on 80-132k graphs — and
the whole loop runs in ONE ``lax.while_loop`` on device: no host round
trips (the tunneled link pays ~90 ms per sync), no data-dependent
shapes.

The B columns share one queue (union frontier), so the kernel wants
(a) locality-ordered node ids and (b) id-clustered target batches —
both guaranteed on the build path: workers own contiguous id ranges
and road inputs are BFS/RCM-reordered first (``cli.reorder``). The
auto gate (``models.cpd.pick_build_kernel``) checks (a) explicitly via
:func:`locality_fraction` and falls back to the dense split kernel on
shuffled ids, where the union wavefront would span the whole graph.

Negative results (round 5, measured on the 264k road graph, same
device window as an 84-90 rows/s baseline — recorded so they are not
re-attempted): (1) a degree-split relax (short-ELL slice for all pops
+ full-width pass for popped hubs) ran 45 rows/s — the extra
nonzero/cumsum/scatter per iteration cost more than the 2.4x gather
reduction saved; (2) degree-BOUNDING the graph (hub tails moved to
zero-weight virtual-node chains, K 20 -> 6-8) kept bit-parity but
inflated iterations 1085 -> 3000-5100 (chain hops serialize across
pops; the unroll only re-relaxes POPPED rows) for 18-65 rows/s;
(3) XLA scatter hints (sorted/unique) on the dist scatter: 9.2 vs 5.8
ms/iter; (4) slot-looped relax accumulation (avoiding the [F, K, B]
temp): within noise. Ablations show no single op dominates — the
iteration is latency-bound through its dependency chain, so the
remaining lever is a fused Pallas pop+relax kernel, not op shaving.

Distances converge to the same unique fixed point as every other
kernel, and first-move extraction reuses the shared full-width pass —
tie-breaking stays bit-identical to the CPU oracle (bench asserts fm
parity on the 264k road graph).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from .device_graph import JINF


@dataclasses.dataclass(frozen=True)
class FrontierGraph:
    """Host-side bundle for the delta-stepping relaxation."""

    in_nbr: np.ndarray   # int32 [N, K_in] k-th in-neighbor (pad: self)
    n: int
    f: int               # pop capacity per iteration
    delta: int           # bucket width (pop window above the queue min)
    s_unroll: int        # relax sub-steps per pop


#: pop capacity: iteration cost is ~flat in F below this on v5e (the
#: fixed loop floor dominates), while the measured optimum across 80k-
#: and 264k-node road graphs sat at 2048 (larger F gathers mostly pad
#: rows once the ready set thins out)
FRONTIER_CAPACITY = 2048

#: bucket width multiplier: delta ~ 32 x mean edge weight pops several
#: wavefront layers per iteration (amortizing the loop floor) while
#: keeping pops ordered enough that re-expansion stays ~1 (measured
#: best at 16-32k on graphs with mean weight ~575)
DELTA_MEAN_W_MULT = 32


def pick_delta(w: np.ndarray) -> int:
    """Bucket width from the weight distribution (power of two).

    Clamped to 2^29 < INF: correctness is delta-independent (any pop
    order converges), and an unclamped width on near-INF mean weights
    would overflow ``prio.min() + delta`` in int32."""
    mean_w = float(w.mean()) if len(w) else 1.0
    target = max(int(min(mean_w * DELTA_MEAN_W_MULT, 1 << 29)), 1)
    return min(1 << (target - 1).bit_length(), 1 << 29)


def locality_fraction(graph, window_mult: int = 8) -> float:
    """Fraction of edges with ``|dst - src|`` under ``window_mult*sqrt(N)``
    — the auto-gate's proxy for "wavefronts are id-coherent". RCM/BFS
    orderings of road graphs measure 0.4-0.6 here; shuffled ids 0.02
    (where the union frontier degenerates to the whole graph and the
    dense kernels win)."""
    if graph.m == 0:
        return 1.0
    win = window_mult * int(np.sqrt(max(graph.n, 1)))
    return float((np.abs(graph.dst - graph.src) < win).mean())


def frontier_graph(graph, f: int | None = None, delta: int | None = None,
                   s_unroll: int = 2) -> FrontierGraph:
    """Build the bundle from a :class:`~..data.graph.Graph`.

    An explicit ``delta`` is clamped to ``pick_delta``'s 2^29 ceiling:
    the pop window computes ``prio.min() + delta`` in int32, and an
    unclamped width would overflow it negative — an empty pop window
    that live-locks the build loop."""
    in_nbr, _ = graph.ell("in")
    return FrontierGraph(
        in_nbr=np.asarray(in_nbr, np.int32), n=graph.n,
        f=f if f is not None else FRONTIER_CAPACITY,
        delta=(min(int(delta), 1 << 29) if delta is not None
               else pick_delta(graph.w)),
        s_unroll=s_unroll)


@functools.lru_cache(maxsize=None)
def _frontier_dist_fn(n: int, f: int, delta: int, s_unroll: int,
                      max_iters: int):
    """Compiled [N, B] batch-minor delta-stepping relaxation."""
    # a queue pops at most F rows per iteration, so the dense kernels'
    # N-1 hop bound does not apply, and no tight a-priori bound exists
    # (a small F drains a saturated queue over many pops — a heuristic
    # limit silently truncated convergence in testing). Termination
    # without a limit is guaranteed: distances only decrease (bounded
    # below) and a node is re-armed only by an improvement, so the
    # queue must empty. max_iters=0 therefore means "run to
    # convergence" with only a runaway backstop; real builds converge
    # in ~1k pops (264k-node road graph, F=2048). NOTE the tunneled
    # device kills single executions past ~1 min — callers bound
    # runtime by batch sizing, and the auto gate's locality check is
    # what keeps iteration counts sane.
    limit = (1 << 30) if max_iters == 0 else max_iters

    @jax.jit
    def dist_to_targets_frontier(out_nbr, out_eid, w_pad, in_nbr, targets):
        b = targets.shape[0]
        valid = targets >= 0
        t_safe = jnp.where(valid, targets, 0)
        dist0 = jnp.full((n, b), JINF, jnp.int32)
        dist0 = dist0.at[t_safe, jnp.arange(b)].set(
            jnp.where(valid, jnp.int32(0), JINF))
        # arm the in-neighbors of every valid target at priority 0 (the
        # only rows with a non-INF relaxation input); pad rows write
        # index n -> dropped
        wake0 = jnp.where(valid[:, None], in_nbr[t_safe, :], n)
        prio0 = jnp.full(n, JINF, jnp.int32).at[wake0.reshape(-1)].min(0)

        def cond(st):
            i, _, prio = st
            return (prio.min() < JINF) & (i < limit)

        def body(st):
            i, dist, prio = st
            theta = prio.min() + delta
            # idle nodes (prio == JINF) must never match the pop window:
            # when theta >= JINF (near-INF weights push prio.min() within
            # delta of JINF), an unmasked pop fills the f slots with
            # low-id idle nodes and starves armed nodes forever —
            # a livelock until the iteration backstop. No overflow:
            # prio <= JINF (1e9) and delta <= 2^29, sum < int32 max.
            idx = jnp.nonzero((prio <= theta) & (prio < JINF),
                              size=f, fill_value=n)[0]
            live = idx < n
            prio = prio.at[idx].set(JINF)             # pads dropped
            nbr = out_nbr[idx]                        # [F, K] (pads clip)
            w = w_pad[out_eid[idx]]                   # [F, K]
            for _ in range(s_unroll):
                via = jnp.minimum(w[:, :, None] + dist[nbr, :], JINF)
                new = via.min(axis=1)                 # [F, B]
                imp = new < dist[idx]                 # [F, B]
                dist = dist.at[idx].min(new)          # pads dropped
                # wake in-neighbors of improved rows at the row's new
                # minimum (their relax input just reached that value);
                # unchanged/pad lanes write index n -> dropped
                newmin = jnp.where(imp, new, JINF).min(axis=1)
                ch = live & (newmin < JINF)
                wake = jnp.where(ch[:, None], in_nbr[idx], n)
                prio = prio.at[wake.reshape(-1)].min(
                    jnp.broadcast_to(newmin[:, None],
                                     wake.shape).reshape(-1))
            return i + 1, dist, prio

        _, d, _ = jax.lax.while_loop(
            cond, body, (jnp.int32(0), dist0, prio0))
        return d.T

    return dist_to_targets_frontier


def build_fm_columns_frontier(dg, fg: FrontierGraph, targets,
                              max_iters: int = 0,
                              extract_chunk: int = 512):
    """CPD shard build via the delta-stepping relaxation; fm extraction
    reuses the full-width pass (bit-identical tie-breaks).

    ``max_iters`` bounds queue POPS (not hop sweeps — a frontier
    iteration advances ~delta of distance, not one hop), 0 = converge.

    ``extract_chunk``: extraction runs in column slices of this many
    targets. The frontier's iteration cost amortizes over the batch
    (B=2048 measured ~10% more rows/s than 512 on the 264k road graph,
    and the fixed fetch/dispatch costs halve again), but a FUSED
    dist+extraction program at B=2048 OOMs: XLA's remat keeps all K
    slot-step temps of the extraction alive at once (20 x [N, B] int32
    = 40 GB observed). Slicing the extraction into separate dispatches
    after the dist solve restores the K-reuse scheduling at any B.
    """
    fn = _frontier_dist_fn(fg.n, fg.f, fg.delta, fg.s_unroll, max_iters)
    t = jnp.asarray(targets)
    dist = fn(dg.out_nbr, dg.out_eid, dg.w_pad,
              jnp.asarray(fg.in_nbr), t)
    b = int(t.shape[0])
    parts = [_extract_jit(dg, t[i:i + extract_chunk],
                          dist[i:i + extract_chunk])
             for i in range(0, b, extract_chunk)]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


@jax.jit
def _extract_jit(dg, t, d):
    """Standalone first-move extraction (one compiled program shared by
    every same-shape column slice of a chunked build)."""
    from .bellman_ford import first_move_from_dist

    return first_move_from_dist(dg, t, d)
