"""CPD construction: batched min-plus Bellman-Ford + first-move extraction.

TPU-native re-expression of the reference's CPD build, which runs one
Dijkstra sweep per owned node under OpenMP (reference ``README.md:88-95``,
``make_cpds.py:20``). Frontier Dijkstra is pointer-chasing and
priority-queue bound — hostile to XLA — so the build is reformulated as
**min-plus fixed-point iteration over a whole batch of targets at once**
(SURVEY.md §7 stage 3):

    dist[b, x]  <-  min(dist[b, x],  min_k  w[eid[x, k]] + dist[b, nbr[x, k]])

where ``nbr/eid`` is the padded ELL out-edge table. Each iteration is one
dense gather + min-reduce over ``[B, N, K]`` — static shapes, fully
vectorized over the batch axis, bandwidth-bound on HBM, and XLA fuses the
add/min into the gather. Convergence (no update anywhere in the batch) exits
a ``lax.while_loop``; the iteration count is the max shortest-path *hop*
length, ~graph diameter.

First moves then fall out in one more pass: the argmin slot of the same
relaxation expression, ties to the smallest slot — matching the CPU oracle's
tie-break exactly (``models.reference.first_move_to_target``).

Distances are directed **node→target** costs: the recurrence gathers over
*out*-edges, so ``dist[b, x] = d(x → targets[b])``, which is precisely the
quantity the target-owning worker needs (queries route by target,
reference ``process_query.py:56-57``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .device_graph import DeviceGraph, JINF


def _relax_nb(dist_nb: jnp.ndarray, dg: DeviceGraph) -> jnp.ndarray:
    """One min-plus relaxation in [N, B] layout.

    The batch axis is **minor**: ``dist_nb[nbr]`` gathers whole contiguous
    ``[B]`` rows (one per (node, slot)), turning the relaxation's memory
    traffic into streaming row reads instead of random scalar gathers — the
    difference between HBM-bandwidth-bound and latency-bound on TPU.
    """
    # [N, K, B]: candidate cost through each out-slot
    via = dg.w_pad[dg.out_eid][:, :, None] + dist_nb[dg.out_nbr, :]
    via = jnp.minimum(via, JINF)
    return jnp.minimum(dist_nb, via.min(axis=1))


@functools.partial(jax.jit, static_argnames=("max_iters", "unroll"))
def dist_to_targets(dg: DeviceGraph, targets: jnp.ndarray,
                    max_iters: int = 0, unroll: int = 1) -> jnp.ndarray:
    """int32 [B, N] of d(x → targets[b]) for every node x.

    ``targets`` int32 [B]; negative entries are padding rows (left all-INF
    except their own source handling) so shard batches can be rectangular.
    ``max_iters`` bounds the loop (0 = N-1, the Bellman-Ford worst case);
    convergence exits early. ``unroll`` relaxations run per loop iteration;
    measured on the bench graph the relaxation is already HBM-bound (the
    gather streams contiguous batch rows), so the default stays 1 — extra
    post-convergence relaxations cost more than the saved loop overhead.
    """
    n = dg.n
    b = targets.shape[0]
    limit = (n - 1) if max_iters == 0 else max_iters
    valid = targets >= 0
    t_safe = jnp.where(valid, targets, 0)
    dist0 = jnp.full((n, b), JINF, jnp.int32)
    dist0 = dist0.at[t_safe, jnp.arange(b)].set(
        jnp.where(valid, jnp.int32(0), JINF))

    def cond(state):
        i, dist, changed = state
        return changed & (i < limit)

    def body(state):
        i, dist, _ = state
        new = dist
        for _ in range(unroll):
            new = _relax_nb(new, dg)
        return i + unroll, new, jnp.any(new < dist)

    # data-derived seed: varying under shard_map (a literal True has
    # replicated type and the carry check rejects it), True iff any valid
    # target row exists
    seed = jnp.any(dist0 < JINF)
    _, dist_nb, _ = jax.lax.while_loop(cond, body,
                                       (jnp.int32(0), dist0, seed))
    return dist_nb.T


@jax.jit
def first_move_from_dist(dg: DeviceGraph, targets: jnp.ndarray,
                         dist: jnp.ndarray) -> jnp.ndarray:
    """First-move table int8 [B, N] from converged distances.

    ``fm[b, x]`` = out-edge slot of x minimizing ``w + d(nbr → targets[b])``
    (first minimal slot on ties, same rule as the CPU oracle). ``-1`` for
    unreachable, for the target row itself, and for padding rows
    (targets[b] < 0).

    The argmin runs as a running scan over the K out-slots (ascending, so
    the FIRST minimal slot wins — ``jnp.argmin`` semantics) in the same
    [N, B] batch-minor layout as the relaxation: a one-shot ``[N, K, B]``
    argmin materializes a K-times-larger temp, which at build batch 512
    on a 264k-node road graph is a 10.8 GB allocation — over HBM.
    """
    dist_nb = dist.T
    best = jnp.full(dist_nb.shape, JINF, jnp.int32)
    fm_nb = jnp.zeros(dist_nb.shape, jnp.int8)
    for k in range(dg.k):
        via_k = jnp.minimum(
            dg.w_pad[dg.out_eid[:, k]][:, None] + dist_nb[dg.out_nbr[:, k]],
            JINF)
        upd = via_k < best
        fm_nb = jnp.where(upd, jnp.int8(k), fm_nb)
        best = jnp.where(upd, via_k, best)
    fm = jnp.where(best.T >= JINF, jnp.int8(-1), fm_nb.T)
    # target's own row: no move
    b = targets.shape[0]
    n = dg.n
    valid = targets >= 0
    t_safe = jnp.where(valid, targets, 0)
    at_target = jax.nn.one_hot(t_safe, n, dtype=jnp.bool_) & valid[:, None]
    fm = jnp.where(at_target, jnp.int8(-1), fm)
    fm = jnp.where(valid[:, None], fm, jnp.int8(-1))
    return fm


def build_fm_columns(dg: DeviceGraph, targets: jnp.ndarray,
                     max_iters: int = 0) -> jnp.ndarray:
    """CPD shard build: first-move columns for a batch of targets.

    One fused device computation: Bellman-Ford to convergence, then
    first-move extraction. Returns int8 [B, N].
    """
    dist = dist_to_targets(dg, targets, max_iters=max_iters)
    return first_move_from_dist(dg, targets, dist)
