"""ELL+COO split relaxation: the build kernel for degree-skewed graphs.

The plain padded-ELL relaxation (``bellman_ford``) gathers ``N x K`` rows
per sweep with K = the MAX out-degree. Road networks are degree-skewed
(the 264k synthetic: K = 20, mean degree 4, p99 = 14 — reference-scale
DIMACS data is the same shape), so ~80% of those gathers hit padding.

Split the adjacency instead:

* a narrow ELL table of width ``K0`` covering every node's first K0
  out-edges (dense rows, streaming gathers), plus
* a COO list of the overflow edges (only hubs have any), relaxed by a
  scatter-min — ``new.at[u].min(w + dist[v])``.

``K0`` minimizes the modeled sweep cost ``N*K0 + SCATTER_COST*overflow``.
First-move extraction still runs ONE pass over the full-width ELL (slot
numbers must index the full out-edge list, and the single pass costs a
sweep, not a build), so tie-breaking stays bit-identical to the CPU
oracle and the plain kernel — tests pin fm parity on skewed graphs.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from .device_graph import JINF

#: modeled cost of one scattered overflow edge relative to one ELL slot
#: (scatter-min lowers to sorted segment ops; measured ~4x a streaming
#: gather row on v5e)
SCATTER_COST = 4


@dataclasses.dataclass(frozen=True)
class ELLSplitGraph:
    """Host-side bundle for the split relaxation (free-flow weights)."""

    nbr0: np.ndarray    # int32 [N, K0] first-K0 neighbors (pad: self)
    w0: np.ndarray      # int32 [N, K0] their weights (pad: JINF)
    u_ov: np.ndarray    # int32 [E_ov] overflow edge sources
    v_ov: np.ndarray    # int32 [E_ov] overflow edge dests
    w_ov: np.ndarray    # int32 [E_ov] overflow edge weights
    k0: int
    n: int


def pick_k0(degrees: np.ndarray, k_max: int) -> int:
    """Width minimizing ``N*K0 + SCATTER_COST * overflow(K0)``."""
    best_k, best_cost = k_max, len(degrees) * k_max
    for k0 in range(1, k_max + 1):
        over = int(np.maximum(degrees - k0, 0).sum())
        cost = len(degrees) * k0 + SCATTER_COST * over
        if cost < best_cost:
            best_k, best_cost = k0, cost
    return best_k


def split_ratio(degrees: np.ndarray, k_max: int) -> tuple[float, int]:
    """Modeled cost of the split vs the plain ELL and the chosen width:
    ``(ratio, k0)`` — ratio < 1 means the split wins."""
    if k_max == 0 or len(degrees) == 0:
        return 1.0, max(k_max, 1)
    k0 = pick_k0(degrees, k_max)
    over = int(np.maximum(degrees - k0, 0).sum())
    return (len(degrees) * k0 + SCATTER_COST * over) / (
        len(degrees) * k_max), k0


def ell_split_graph(graph, k0: int | None = None) -> ELLSplitGraph:
    """Build the split bundle from a :class:`~..data.graph.Graph`.

    ``k0`` skips the width search when the caller already ran it
    (``models.cpd.pick_build_kernel`` gates on :func:`split_ratio` and
    passes its k0 through).
    """
    nbr, eid = graph.ell("out")
    k_max = nbr.shape[1]
    if k0 is None:
        k0 = pick_k0(np.diff(graph.out_ptr), k_max)
    w_padded = graph.padded_weights()          # [m+1], last = INF
    nbr0 = np.asarray(nbr[:, :k0], np.int32)
    w0 = np.asarray(w_padded[eid[:, :k0]], np.int32)
    over_mask = eid[:, k0:] < graph.m          # real edges beyond K0
    # row-major flatten of the mask keeps overflow edges u-sorted by
    # construction (scatter locality needs no extra sort)
    rows = np.repeat(np.arange(graph.n), over_mask.sum(axis=1))
    flat_eid = eid[:, k0:][over_mask]
    return ELLSplitGraph(
        nbr0=nbr0, w0=w0,
        u_ov=np.asarray(rows, np.int32),
        v_ov=np.asarray(graph.dst[flat_eid], np.int32),
        w_ov=np.asarray(w_padded[flat_eid], np.int32),
        k0=k0, n=graph.n)


@functools.lru_cache(maxsize=None)
def _ellsplit_dist_fn(n: int, k0: int, n_ov: int, max_iters: int):
    """Compiled [N, B] batch-minor split relaxation to convergence."""
    limit = (n - 1) if max_iters == 0 else max_iters

    @jax.jit
    def dist_to_targets_split(nbr0, w0, u_ov, v_ov, w_ov, targets):
        b = targets.shape[0]
        valid = targets >= 0
        t_safe = jnp.where(valid, targets, 0)
        dist0 = jnp.full((n, b), JINF, jnp.int32)
        dist0 = dist0.at[t_safe, jnp.arange(b)].set(
            jnp.where(valid, jnp.int32(0), JINF))

        def relax(d):
            via = jnp.minimum(w0[:, :, None] + d[nbr0, :], JINF)
            nd = jnp.minimum(d, via.min(axis=1))
            if n_ov:
                cand = jnp.minimum(w_ov[:, None] + d[v_ov, :], JINF)
                nd = nd.at[u_ov].min(cand)
            return nd

        def cond(st):
            i, d, ch = st
            return ch & (i < limit)

        def body(st):
            i, d, _ = st
            nd = relax(d)
            return i + 1, nd, jnp.any(nd < d)

        # data-derived seed: varying under shard_map (a literal True has
        # replicated type and the carry check rejects it)
        seed = jnp.any(dist0 < JINF)
        _, d, _ = jax.lax.while_loop(cond, body,
                                     (jnp.int32(0), dist0, seed))
        return d.T

    return dist_to_targets_split


def build_fm_columns_ellsplit(dg, sg: ELLSplitGraph, targets,
                              max_iters: int = 0):
    """CPD shard build via the split relaxation; fm extraction reuses the
    full-width pass (bit-identical tie-breaks)."""
    from .bellman_ford import first_move_from_dist

    fn = _ellsplit_dist_fn(sg.n, sg.k0, len(sg.u_ov), max_iters)
    dist = fn(jnp.asarray(sg.nbr0), jnp.asarray(sg.w0),
              jnp.asarray(sg.u_ov), jnp.asarray(sg.v_ov),
              jnp.asarray(sg.w_ov), jnp.asarray(targets))
    return first_move_from_dist(dg, jnp.asarray(targets), dist)
