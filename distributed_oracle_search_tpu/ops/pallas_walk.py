"""Pallas-fused ``table-search`` walk kernel (ROADMAP item 1).

The XLA walk (:func:`.table_search.table_search_batch`) is scalar-
gather-throughput bound: every step issues generic XLA gathers (fm slot
+ packed (next, weight) pair) that round-trip HBM, and the bench pins
per-query TPU throughput at 0.71x one CPU core while the bulk dist
path — one gather per query — runs 2.5x. This module re-expresses the
same walk as ONE Pallas kernel so the per-step state never leaves the
chip:

* **grid = the bucket split.** ``pick_buckets`` (the ``BUCKET_LANES`` /
  ``BUCKET_MAX`` auto-bucketing the XLA kernel scans over) becomes the
  kernel grid: one program per bucket, each walking its own
  ``while_loop`` to its own max length. TPU grid programs run
  sequentially on a core, so scratch persists across buckets — which is
  what makes the double buffer below work.
* **double-buffered first-move row tiles.** Each bucket's queries need
  ``qb`` first-move rows (``fm[t_rows[q]]``, one row per lane, fixed
  for the whole walk). The row ids arrive via scalar prefetch
  (``PrefetchScalarGridSpec``), and the loader DMAs bucket ``i+1``'s
  rows into the spare VMEM tile slot while bucket ``i`` walks — the
  next bucket's first gather never waits on HBM. Under interpret mode
  (the CPU tier-1 path) TPU DMA semaphores don't exist, so the loader
  degrades to a direct ref copy with identical semantics.
* **fused diff application.** Costs accumulate on the QUERY-TIME
  weights inside the same loop (``w_query_pad[out_eid[x, slot]]``) —
  free-flow moves, diffed costs, exactly the module-header contract of
  ``ops.table_search``.

**The row-tile loader is a seam — now occupied.** ``_stage_row_direct``
/ ``_stage_row_dma`` materialize one fm row into one tile lane, and the
compressed-CPD tier (ROADMAP item 1 after the PR 10 re-anchor;
``models.resident``) plugs in here: under ``packed4=True`` the loaders
stage the PACK4 nibble row — half the HBM traffic — and the walk
widens it with an on-chip nibble unpack, so raw rows only ever exist
in VMEM. RLE-resident shards decompress per batch through the XLA
run-start search (``CompressedFM.decompress_rows``) before either
kernel runs; the walk loop itself never changes.

Kernel selection (``DOS_WALK_KERNEL``, via ``utils.env``):

=========  ==========================================================
``auto``   Pallas on real TPU backends, XLA everywhere else (default)
``pallas`` force the fused kernel (interpret-mode on non-TPU hosts —
           the parity/testing path, orders slower than XLA on CPU)
``xla``    force the existing XLA walk (the reference implementation
           and the CPU tier-1 path)
=========  ==========================================================

``auto``/``pallas`` additionally fall back to XLA when the bucket's
row tile + graph tables exceed the VMEM budget
(``DOS_WALK_VMEM_MB``) — an oversized shard degrades to the reference
path, never faults on-chip.

Semantics are exactly :func:`.table_search.table_search_batch`'s
(itself pinned to ``models.reference.table_search_walk``): free-flow
first moves, query-time costs, ``-1``/unreachable and ``k_moves``
budget stops, ``plen`` = edges followed, pad lanes halted at birth.
Answers are bit-identical to the XLA path — pinned by
``tests/test_pallas_walk.py`` in interpret mode under the CPU tier-1
run.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.env import env_cast, env_str
from ..utils.log import get_logger
from .device_graph import DeviceGraph
from .table_search import pick_buckets

log = get_logger(__name__)

#: accepted DOS_WALK_KERNEL spellings; anything else degrades to auto
WALK_KERNELS = ("auto", "pallas", "xla")

#: default per-core VMEM budget (MB) the fused kernel may claim for its
#: double-buffered row tile + resident graph tables; v5e exposes ~16 MB
#: and the compiler needs headroom for the walk state itself
_VMEM_BUDGET_MB = 10.0


def walk_kernel_choice() -> str:
    """The raw ``DOS_WALK_KERNEL`` knob: ``auto`` / ``pallas`` /
    ``xla``; malformed values degrade to ``auto`` with a log line
    (the shared ``utils.env`` policy)."""
    raw = (env_str("DOS_WALK_KERNEL", "auto") or "auto").strip().lower()
    if raw not in WALK_KERNELS:
        log.warning("ignoring malformed DOS_WALK_KERNEL=%r (using "
                    "'auto'; valid: %s)", raw, "/".join(WALK_KERNELS))
        return "auto"
    return raw


def resolve_walk_kernel(backend: str | None = None) -> str:
    """Resolve the knob to a concrete kernel: ``auto`` picks Pallas on
    real TPU backends and the XLA walk everywhere else (interpret-mode
    Pallas is a correctness tool, not a serving path)."""
    choice = walk_kernel_choice()
    if choice != "auto":
        return choice
    platform = backend or jax.default_backend()
    return "pallas" if platform == "tpu" else "xla"


def pallas_walk_fits(n: int, k: int, m: int, q: int,
                     n_buckets: int = 0,
                     codec: str = "raw") -> tuple[bool, str]:
    """Would the fused kernel's VMEM working set fit the budget?

    ``n``/``k``/``m`` are the graph's node count, max out-degree, and
    edge count; ``q`` the (padded) batch size. The working set counts
    what the kernel actually holds live per bucket: the double-buffered
    row tile (int8 ``2 * qb * n``, HALVED to nibble width under
    ``codec="pack4"`` — the compressed working set, ROADMAP item 1)
    PLUS the loop-resident int32 widening of the active slot
    (``tl = ...astype(int32)`` — 4 bytes/lane, the dominant consumer;
    the pack4 unpack holds one extra int32 byte-gather temp of the same
    size while it widens), and the graph tables both as staged blocks
    and as their flattened loop copies. Returns ``(ok, reason)`` so
    callers can log the degrade once.
    """
    if q <= 0:
        return True, ""
    nb = pick_buckets(q, n_buckets)
    qb = q // nb
    if codec == "pack4":
        tile = 2 * qb * ((n + 1) // 2)         # uint8 nibbles, 2 slots
        unpack_tmp = 4 * qb * n                # int32 byte-gather temp
    else:
        tile = 2 * qb * n                      # int8 rows, two slots
        unpack_tmp = 0
    tile_widened = 4 * qb * n                  # int32 active-slot copy
    # nbr + eid + w_pad int32, staged block + flattened loop copy
    tables = 2 * (2 * n * k * 4 + (m + 1) * 4)
    budget_mb = env_cast("DOS_WALK_VMEM_MB", _VMEM_BUDGET_MB, float)
    if budget_mb <= 0:
        budget_mb = _VMEM_BUDGET_MB
    need = tile + tile_widened + unpack_tmp + tables
    if need > budget_mb * 2**20:
        return False, (
            f"fused-walk working set {need / 2**20:.1f} MB "
            f"({codec} tile 2x{qb} rows + int32 widening + tables) over "
            f"the {budget_mb:.0f} MB VMEM budget (DOS_WALK_VMEM_MB) — "
            "falling back to the XLA walk")
    return True, ""


def choose_walk_kernel(n: int, k: int, m: int, q: int,
                       codec: str = "raw") -> tuple[str, str]:
    """The one selection site both serving paths call: resolve the
    ``DOS_WALK_KERNEL`` knob, then degrade an over-budget pallas
    request to the XLA walk. ``codec`` names the tile the kernel would
    stage (``pack4`` = the compressed-resident nibble tile). Returns
    ``(kernel, why)`` — ``why`` is non-empty exactly when a pallas
    request fell back, so callers own only their log-once bookkeeping,
    never the policy."""
    kernel = resolve_walk_kernel()
    if kernel != "pallas":
        return kernel, ""
    fits, why = pallas_walk_fits(n, k, m, q, codec=codec)
    if not fits:
        return "xla", why
    return "pallas", ""


# ----------------------------------------------------- row-tile loaders
#
# THE SEAM: one fm row -> one VMEM tile lane. Everything the walk knows
# about where rows come from lives in these two functions. The
# compressed-CPD tier (ROADMAP item 1) uses them unchanged: under
# ``packed4`` the "row" being staged is the pack4 NIBBLE row (the tile
# narrows to ceil(n/2) uint8), and decompression happens after the
# stage — an on-chip nibble unpack where the raw path only widens to
# int32 — so the walk loop below never changes.

#: pack4 marker nibble for -1 (the streamed wire format's vocabulary,
#: models.resident.PACK4_MARKER — duplicated: ops must not import
#: models)
_PACK4_MARKER = 15

def _stage_row_direct(fm_ref, tile, j, row):
    """Interpret-mode loader: plain ref copy (TPU DMA semaphores do not
    exist under the Pallas interpreter)."""
    tile[j, :] = fm_ref[row, :]


def _stage_row_dma(fm_ref, tile, sem, slot, j, row, wait: bool):
    """Hardware loader: async HBM->VMEM copy of one row into tile slot
    ``slot``, lane ``j``. ``wait=False`` starts the copy (the double
    buffer's prefetch half), ``wait=True`` blocks on it."""
    cp = pltpu.make_async_copy(fm_ref.at[row], tile.at[slot, j],
                               sem.at[slot])
    if wait:
        cp.wait()
    else:
        cp.start()


def _make_kernel(nb: int, qb: int, n: int, k: int, limit: int,
                 unroll: int, budget: int | None, use_dma: bool,
                 packed4: bool):
    """Build the per-bucket kernel body (static shapes baked in).

    ``budget`` is the per-step ``k_moves`` cap (None = the unlimited
    reference default — the compare vanishes from the program, same
    static specialization as the XLA kernel's). ``packed4``: the fm
    ref holds pack4 nibble rows (``models.resident``) — the staging
    copies move the HALF-width uint8 rows and the widening step
    becomes decompress-on-tile (nibble unpack, 15 -> -1).
    """

    def _stage_bucket(rows_sref, fm_ref, tile, sem, slot, base,
                      wait: bool):
        # one loader call per lane; rows arrive via scalar prefetch so
        # the indices exist before the bucket's compute does
        def stage(j, _):
            row = rows_sref[base + j]
            if use_dma:
                _stage_row_dma(fm_ref, tile, sem, slot, j, row, wait)
            else:
                _stage_row_direct(fm_ref, tile, j, row)
            return 0

        jax.lax.fori_loop(0, qb, stage, 0)

    def widen(staged):
        """Staged tile slot -> the int32 [qb, n] slot table the walk
        gathers from. Raw tiles only widen; pack4 tiles DECOMPRESS
        here — a byte gather + nibble shift per column, the on-chip
        half of the compressed-resident scheme."""
        if not packed4:
            return staged.astype(jnp.int32)
        pk = staged.astype(jnp.int32)                  # [qb, ceil(n/2)]
        cols = jnp.arange(n, dtype=jnp.int32)
        byte = jnp.take(pk, cols // 2, axis=1)         # [qb, n]
        v = (byte >> ((cols % 2) * 4)) & 0xF
        return jnp.where(v == _PACK4_MARKER, jnp.int32(-1), v)

    def kernel(rows_sref, s_ref, t_ref, valid_ref, fm_ref, nbr_ref,
               eid_ref, w_ref, cost_ref, plen_ref, fin_ref, tile,
               *dma_scratch):
        i = pl.program_id(0)
        if use_dma:
            # double buffer: program 0 stages its own tile; every
            # program then prefetches bucket i+1 into the spare slot
            # BEFORE walking, so the next bucket's rows stream in
            # behind this bucket's compute
            (sem,) = dma_scratch
            cur = jax.lax.rem(i, 2)
            nxt = jax.lax.rem(i + 1, 2)

            @pl.when(i == 0)
            def _():
                _stage_bucket(rows_sref, fm_ref, tile, sem, 0, 0,
                              wait=False)

            @pl.when(i + 1 < nb)
            def _():
                _stage_bucket(rows_sref, fm_ref, tile, sem, nxt,
                              (i + 1) * qb, wait=False)

            _stage_bucket(rows_sref, fm_ref, tile, sem, cur, i * qb,
                          wait=True)
            tl = widen(tile[cur])                          # [qb, n]
        else:
            sem = None
            _stage_bucket(rows_sref, fm_ref, tile, sem, 0, i * qb,
                          wait=False)
            tl = widen(tile[...])                          # [qb, n]

        s_v = s_ref[0, :]
        t_v = t_ref[0, :]
        vld = valid_ref[0, :]
        # graph tables resident in VMEM for the whole walk (flattened
        # once: the per-step gather is nbr/eid[x * k + slot])
        nbr_f = nbr_ref[...].reshape(-1)
        eid_f = eid_ref[...].reshape(-1)
        w_f = w_ref[...].reshape(-1)

        def fm_slot(x):
            # the fused first-move gather: lane j reads ITS row's slot
            # from the staged tile — VMEM, never HBM, never XLA gather
            return jnp.take_along_axis(tl, x[:, None], axis=1)[:, 0]

        # same birth rule as the XLA scaffold: pad lanes start at t
        # (zero-length) and halted; real lanes halt on a -1 first move
        x0 = jnp.where(vld, s_v, t_v)
        halted0 = (fm_slot(x0) < 0) | ~vld
        state0 = (jnp.int32(0), x0, x0 * 0, x0 * 0, halted0)

        def cond(state):
            it, _, _, _, halted = state
            return (~jnp.all(halted)) & (it < limit)

        def step(x, cost, plen, halted):
            slot = fm_slot(x)
            can = (~halted) & (slot >= 0)
            if budget is not None:
                can &= plen < budget
            flat = x * k + jnp.maximum(slot, 0)
            # query-time weight application, fused into the walk: the
            # diffed w_pad is gathered per step, moves stay free-flow
            wt = jnp.take(w_f, jnp.take(eid_f, flat))
            cost = jnp.where(can, cost + wt, cost)
            plen = jnp.where(can, plen + 1, plen)
            x = jnp.where(can, jnp.take(nbr_f, flat), x)
            halted = halted | ~can
            return x, cost, plen, halted

        def body(state):
            it, x, cost, plen, halted = state
            for _ in range(unroll):
                x, cost, plen, halted = step(x, cost, plen, halted)
            return it + unroll, x, cost, plen, halted

        _, x, cost, plen, _ = jax.lax.while_loop(cond, body, state0)
        fin = (x == t_v) & vld
        cost_ref[0, :] = jnp.where(vld, cost, 0)
        plen_ref[0, :] = jnp.where(vld, plen, 0)
        fin_ref[0, :] = fin

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("k_moves", "max_steps", "unroll",
                                    "n_buckets", "interpret", "packed4"))
def _pallas_walk(dg: DeviceGraph, fm, t_rows, s, t, w_query_pad, valid,
                 k_moves: int, max_steps: int, unroll: int,
                 n_buckets: int, interpret: bool, packed4: bool):
    q = s.shape[0]
    n = dg.n
    k = dg.k
    limit = n if max_steps == 0 else max_steps
    unlimited = k_moves < 0 and max_steps == 0
    budget = None if unlimited else (limit if k_moves < 0 else k_moves)
    nb = n_buckets
    qb = q // nb

    rows32 = t_rows.astype(jnp.int32)
    s2 = s.astype(jnp.int32).reshape(nb, qb)
    t2 = t.astype(jnp.int32).reshape(nb, qb)
    v2 = valid.reshape(nb, qb)
    w2 = w_query_pad.astype(jnp.int32).reshape(1, -1)

    kernel = _make_kernel(nb, qb, n, k, limit, unroll, budget,
                          use_dma=not interpret, packed4=packed4)
    # the staged tile matches the fm row width: full int8 rows raw,
    # half-width uint8 nibble rows under pack4 residency
    width = int(fm.shape[1])
    tile_shape = ((2, qb, width) if not interpret else (qb, width))
    scratch = [pltpu.VMEM(tile_shape, fm.dtype)]
    if not interpret:
        scratch.append(pltpu.SemaphoreType.DMA((2,)))

    bucket_spec = pl.BlockSpec((1, qb), lambda i, sref: (i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[
            bucket_spec,                                   # s
            bucket_spec,                                   # t
            bucket_spec,                                   # valid
            pl.BlockSpec(memory_space=pltpu.ANY),          # fm (HBM)
            pl.BlockSpec((n, k), lambda i, sref: (0, 0)),  # out_nbr
            pl.BlockSpec((n, k), lambda i, sref: (0, 0)),  # out_eid
            pl.BlockSpec((1, w2.shape[1]),
                         lambda i, sref: (0, 0)),          # w_query_pad
        ],
        out_specs=[bucket_spec, bucket_spec, bucket_spec],
        scratch_shapes=scratch,
    )
    cost, plen, fin = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nb, qb), jnp.int32),
            jax.ShapeDtypeStruct((nb, qb), jnp.int32),
            jax.ShapeDtypeStruct((nb, qb), jnp.bool_),
        ],
        interpret=interpret,
    )(rows32, s2, t2, v2, fm, dg.out_nbr, dg.out_eid, w2)
    return cost.reshape(q), plen.reshape(q), fin.reshape(q)


def pallas_walk_batch(dg: DeviceGraph, fm, t_rows, s, t, w_query_pad,
                      valid=None, k_moves: int = -1, max_steps: int = 0,
                      unroll: int = 8, n_buckets: int = 0,
                      interpret: bool | None = None,
                      packed4: bool = False):
    """Fused-kernel drop-in for
    :func:`.table_search.table_search_batch` — same parameters, same
    ``(cost, plen, finished)`` contract, bit-identical answers.

    ``interpret``: None = auto (interpret everywhere but real TPU —
    how the CPU tier-1 parity suite executes the kernel); the
    remaining knobs mirror the XLA kernel's and share
    :func:`.table_search.pick_buckets` as the grid resolver.

    ``packed4``: ``fm`` is the pack4-compressed resident shard
    (``[R, ceil(N/2)]`` uint8 nibble rows, ``models.resident``); the
    row-tile loader stages the packed rows and the kernel unpacks
    on-chip — decompress inside the staging DMA, the compressed
    working set :func:`pallas_walk_fits` accounts under
    ``codec="pack4"``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q = s.shape[0]
    if q == 0:
        z = jnp.zeros((0,), jnp.int32)
        return z, z, jnp.zeros((0,), jnp.bool_)
    if valid is None:
        valid = jnp.ones((q,), jnp.bool_)
    return _pallas_walk(dg, fm, t_rows, s, t, w_query_pad, valid,
                        int(k_moves), int(max_steps), int(unroll),
                        pick_buckets(q, int(n_buckets)),
                        bool(interpret), bool(packed4))
