"""Fast-sweeping min-plus relaxation: CPD builds in O(turns), not O(hops).

The sweep-per-hop relaxations (``bellman_ford``, ``shift_relax``) need
~hop-diameter iterations — ``O(width+height)`` on a grid city, which makes
the per-row build cost grow with graph size and walls the build off beyond
~50k nodes (measured: 165 s full build at 224x224 on v5e).

This module re-expresses the classic **fast sweeping method** as TPU scans.
One "cycle" runs four Gauss-Seidel sweeps, one per diagonal quadrant
ordering; each sweep processes anti-diagonals sequentially, so a distance
value propagates along an ENTIRE monotone staircase path in a single sweep.
Cycles needed ≈ the number of quadrant reversals / off-lattice hops on
shortest paths — independent of the hop diameter.

The TPU trick is the **skewed layout**: storing ``D_skew[y, x+y] = D[y, x]``
makes every anti-diagonal a contiguous column, and both in-quadrant
dependencies of column ``c`` — the same-row neighbor ``(x-1, y)`` and the
cross-row neighbor ``(x, y-1)`` — live in column ``c-1``. A quadrant sweep
is then one ``lax.scan`` over columns whose body is a tiny [H, B]
elementwise min-plus update (carry = previous column, already updated:
exactly Gauss-Seidel). The scan is **blocked**: ``_GROUP`` anti-diagonals
per scan step, sequentially unrolled inside the body, so step-dispatch
overhead amortizes while the Gauss-Seidel chain stays exact. Static
shapes, no gathers in the scan body; the two skew/unskew row-gathers per
sweep are O(N*B) once.

Off-lattice edges are split by ``Graph.grid_split``: frequent constant
id-offsets (arterial shortcuts) become shift planes relaxed by pad+slice —
pure VPU adds, no gather, no [N, K, B] temp — and only true stragglers pay
a (narrow) padded-ELL gather, both once per cycle. Correctness never
depends on the grid assumption — only speed does: min-plus relaxation
converges to the same fixed point under any update order, so the result is
bit-identical to ``bellman_ford.dist_to_targets`` (tests pin this).

Reference parity: this replaces the per-node Dijkstra sweeps of
``make_cpd_auto`` (reference ``make_cpds.py:20``, ``README.md:88-95``)
as the third and fastest build kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .device_graph import JINF

#: anti-diagonals per scan step (sequentially unrolled in the body)
_GROUP = 8


class GridGraph:
    """Host-side bundle of ``Graph.grid_split`` outputs, device-ready.

    ``width``/``height``/``shifts`` are static (baked into the compiled
    program); the weight arrays are jit inputs, so one program serves any
    graph with the same dimensions and shift signature.
    """

    def __init__(self, width, height, wl, wr, wd, wu, shifts, w_shift,
                 src_left, dst_left, w_left):
        self.width = int(width)
        self.height = int(height)
        n = self.width * self.height
        on_grid = sum(int((np.asarray(a) < int(JINF)).sum())
                      for a in (wl, wr, wd, wu))
        on_shift = int((np.asarray(w_shift) < int(JINF)).sum())
        left = int(len(np.asarray(src_left)))
        total = on_grid + on_shift + left
        self._coverage = 1.0 if total == 0 else (on_grid + on_shift) / total
        # lattice share only: what the quadrant scans themselves serve.
        # The auto build-method gate keys on this — a graph whose edges are
        # all shift planes is correct under sweep but gains nothing from it
        self._lattice_coverage = 0.0 if total == 0 else on_grid / total
        self.wl = jnp.asarray(wl, jnp.int32).reshape(height, width)
        self.wr = jnp.asarray(wr, jnp.int32).reshape(height, width)
        self.wd = jnp.asarray(wd, jnp.int32).reshape(height, width)
        self.wu = jnp.asarray(wu, jnp.int32).reshape(height, width)
        self.shifts = tuple(int(s) for s in shifts)
        self.w_shift = jnp.asarray(w_shift, jnp.int32)
        self.src_left = jnp.asarray(src_left, jnp.int32)
        self.dst_left = jnp.asarray(dst_left, jnp.int32)
        self.w_left = jnp.asarray(w_left, jnp.int32)
        self.n = n

    @classmethod
    def from_graph(cls, graph, width: int | None = None):
        split = graph.grid_split(width)
        if split is None:
            return None
        return cls(*split)

    @property
    def n_left(self) -> int:
        return int(self.src_left.shape[0])

    def coverage(self) -> float:
        return self._coverage

    def lattice_coverage(self) -> float:
        return self._lattice_coverage


@functools.lru_cache(maxsize=None)
def _sweep_dist_fn(h: int, w: int, shifts: tuple, n_left: int,
                   max_iters: int):
    n = h * w
    ca = w + h - 1                      # anti-diagonal count, both skews
    ca_pad = -(-ca // _GROUP) * _GROUP  # blocked-scan padding (tail INF)
    limit = (n - 1) if max_iters == 0 else max_iters
    shift_pad = max((abs(s) for s in shifts), default=0)

    ys = jnp.arange(h, dtype=jnp.int32)[:, None]        # [H, 1]
    cols = jnp.arange(ca_pad, dtype=jnp.int32)[None, :]  # [1, CApad]
    # layout A: col = x + y          layout B: col = x - y + (h-1)
    x_a = cols - ys
    x_b = cols - (h - 1) + ys
    ok_a, xc_a = (x_a >= 0) & (x_a < w), jnp.clip(x_a, 0, w - 1)
    ok_b, xc_b = (x_b >= 0) & (x_b < w), jnp.clip(x_b, 0, w - 1)
    xs_plain = jnp.arange(w, dtype=jnp.int32)[None, :]
    c_of_plain_a = xs_plain + ys                        # [H, W]
    c_of_plain_b = xs_plain - ys + (h - 1)

    def skew_w(w_hw, xc, ok):          # [H, W] weights -> [CApad, H]
        sk = jnp.take_along_axis(w_hw, xc, axis=1)
        return jnp.where(ok, sk, JINF).T

    def to_skew(d, xc, ok):            # [H, W, B] -> [CApad, H, B]
        sk = jnp.take_along_axis(d, xc[:, :, None], axis=1)
        return jnp.swapaxes(jnp.where(ok[:, :, None], sk, JINF), 0, 1)

    def from_skew(sk, c_plain):        # [CApad, H, B] -> [H, W, B]
        return jnp.take_along_axis(jnp.swapaxes(sk, 0, 1),
                                   c_plain[:, :, None], axis=1)

    def row_down(prev):                # value of row y-1 aligned to row y
        return jnp.concatenate(
            [jnp.full_like(prev[:1], JINF), prev[:-1]], axis=0)

    def row_up(prev):                  # value of row y+1 aligned to row y
        return jnp.concatenate(
            [prev[1:], jnp.full_like(prev[:1], JINF)], axis=0)

    def sweep(d, xc, ok, c_plain, w_same, w_cross, cross, reverse):
        """One quadrant Gauss-Seidel sweep: blocked scan over diagonals."""
        sk = to_skew(d, xc, ok)
        g = _GROUP
        blk = lambda a: a.reshape(ca_pad // g, g, *a.shape[1:])  # noqa: E731

        def step(prev, inp):
            cur, wsm, wcr = inp        # [G,H,B], [G,H], [G,H]
            out = [None] * g
            order = range(g - 1, -1, -1) if reverse else range(g)
            for gi in order:
                via = jnp.minimum(
                    jnp.minimum(wsm[gi][:, None] + prev, JINF),
                    jnp.minimum(wcr[gi][:, None] + cross(prev), JINF))
                prev = jnp.minimum(cur[gi], via)
                out[gi] = prev
            return prev, jnp.stack(out)

        # data-derived init (sk*0 + JINF): under shard_map a constant
        # carry has replicated type while the body output is
        # mesh-varying, and the scan carry check rejects the mix
        init = sk[0] * 0 + JINF
        _, out = jax.lax.scan(step, init,
                              (blk(sk), blk(w_same), blk(w_cross)),
                              reverse=reverse)
        return from_skew(out.reshape(ca_pad, *out.shape[2:]), c_plain)

    def relax_shifts(flat, w_shift):   # [N, B] pad+slice shift planes
        if not shifts:
            return flat
        dp = jnp.pad(flat, ((shift_pad, shift_pad), (0, 0)),
                     constant_values=JINF)
        acc = flat
        for si, s in enumerate(shifts):
            sh = jax.lax.slice_in_dim(dp, shift_pad + s, shift_pad + s + n,
                                      axis=0)
            acc = jnp.minimum(acc,
                              jnp.minimum(w_shift[si][:, None] + sh, JINF))
        return acc

    @jax.jit
    def dist_to_targets_sweep(wl, wr, wd, wu, w_shift, src_left, dst_left,
                              w_left, targets):
        b = targets.shape[0]
        valid = targets >= 0
        t_safe = jnp.where(valid, targets, 0)
        flat0 = jnp.full((n, b), JINF, jnp.int32)
        flat0 = flat0.at[t_safe, jnp.arange(b)].set(
            jnp.where(valid, jnp.int32(0), JINF))
        d0 = flat0.reshape(h, w, b)

        # skewed per-layout weight planes (computed once, loop-invariant)
        wl_a, wd_a = skew_w(wl, xc_a, ok_a), skew_w(wd, xc_a, ok_a)
        wr_a, wu_a = skew_w(wr, xc_a, ok_a), skew_w(wu, xc_a, ok_a)
        wl_b, wu_b = skew_w(wl, xc_b, ok_b), skew_w(wu, xc_b, ok_b)
        wr_b, wd_b = skew_w(wr, xc_b, ok_b), skew_w(wd, xc_b, ok_b)

        def off_lattice(d):
            """Shortcut shift planes + straggler scatter-min, once per
            cycle: shortcut edges reseed the next cycle\'s sweeps."""
            if not shifts and not n_left:
                return d
            flat = d.reshape(n, b)
            flat = relax_shifts(flat, w_shift)
            if n_left:
                via = jnp.minimum(w_left[:, None] + flat[dst_left, :], JINF)
                flat = flat.at[src_left, :].min(via)
            return flat.reshape(h, w, b)

        def cycle(d):
            # quadrant (+,+): deps (x-1,y) same-row, (x,y-1) row below
            d = sweep(d, xc_a, ok_a, c_of_plain_a, wl_a, wd_a, row_down,
                      reverse=False)
            # quadrant (-,-): deps (x+1,y), (x,y+1)
            d = sweep(d, xc_a, ok_a, c_of_plain_a, wr_a, wu_a, row_up,
                      reverse=True)
            # quadrant (+,-): deps (x-1,y), (x,y+1)
            d = sweep(d, xc_b, ok_b, c_of_plain_b, wl_b, wu_b, row_up,
                      reverse=False)
            # quadrant (-,+): deps (x+1,y), (x,y-1)
            d = sweep(d, xc_b, ok_b, c_of_plain_b, wr_b, wd_b, row_down,
                      reverse=True)
            return off_lattice(d)

        def cond(st):
            i, _, changed = st
            return changed & (i < limit)

        def body(st):
            i, d, _ = st
            nd = cycle(d)
            return i + 1, nd, jnp.any(nd < d)

        # data-derived seed: varying under shard_map, True iff any valid
        # target row exists (an all-padding chunk converges in zero cycles)
        seed = jnp.any(flat0 < JINF)
        _, d, _ = jax.lax.while_loop(cond, body, (jnp.int32(0), d0, seed))
        return d.reshape(n, b).T

    return dist_to_targets_sweep


def dist_to_targets_sweep(gg: GridGraph, targets, max_iters: int = 0):
    """int32 [B, N] of d(x → targets[b]) via fast-sweeping scans.

    Bit-identical to ``bellman_ford.dist_to_targets`` (same min-plus fixed
    point; tests pin equality). ``max_iters`` bounds the CYCLE count
    (each cycle = 4 quadrant sweeps + 1 off-lattice relax); 0 = converge.
    """
    fn = _sweep_dist_fn(gg.height, gg.width, gg.shifts, gg.n_left,
                        max_iters)
    return fn(gg.wl, gg.wr, gg.wd, gg.wu, gg.w_shift, gg.src_left,
              gg.dst_left, gg.w_left, jnp.asarray(targets, jnp.int32))


def build_fm_columns_sweep(dg, gg: GridGraph, targets, max_iters: int = 0):
    """CPD build via fast sweeping + the shared first-move extraction
    (tie-break identical to the ELL and shift paths)."""
    from .bellman_ford import first_move_from_dist

    dist = dist_to_targets_sweep(gg, targets, max_iters=max_iters)
    return first_move_from_dist(dg, jnp.asarray(targets, jnp.int32), dist)
