"""Device-resident graph arrays.

The TPU-side graph representation: the padded ELL layout of
``data.Graph`` as jnp arrays, ready for gather-based relaxation. Static
shapes only — ``[N, K]`` neighbor/edge tables and a ``[M+1]`` weight vector
whose last slot is INF so ELL padding lanes can never win a min (see
``data.graph.Graph.ell``).

This plays the role warthog's graph loader plays for the C++ engine
(SURVEY.md §C5): everything downstream (CPD build, table-search) consumes
only these arrays.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..data.graph import Graph, INF


class DeviceGraph(NamedTuple):
    """ELL graph on device.

    out_nbr : int32 [N, K] — k-th out-neighbor (self for padding)
    out_eid : int32 [N, K] — edge id (M for padding)
    w_pad   : int32 [M+1]  — free-flow weights; w_pad[M] = INF
    """
    out_nbr: jnp.ndarray
    out_eid: jnp.ndarray
    w_pad: jnp.ndarray

    @property
    def n(self) -> int:
        return self.out_nbr.shape[0]

    @property
    def k(self) -> int:
        return self.out_nbr.shape[1]

    @classmethod
    def from_graph(cls, g: Graph, weights: np.ndarray | None = None
                   ) -> "DeviceGraph":
        nbr, eid = g.ell("out")
        return cls(
            out_nbr=jnp.asarray(nbr, jnp.int32),
            out_eid=jnp.asarray(eid, jnp.int32),
            w_pad=jnp.asarray(g.padded_weights(weights), jnp.int32),
        )

    def with_weights(self, w_pad: jnp.ndarray) -> "DeviceGraph":
        """Same topology, different (e.g. congestion-perturbed) weights."""
        return self._replace(w_pad=jnp.asarray(w_pad, jnp.int32))


JINF = jnp.int32(INF)
