"""Gather-free min-plus relaxation over shift-structured edges.

The ELL-gather relaxation (``bellman_ford._relax_nb``) is bound by TPU
scalar-gather throughput (~21 G gathered elements/s measured on v5e). But
road networks with locality-preserving node ids (grid row-major, RCM/BFS
orderings) put ~98% of edges at a handful of constant id-offsets
``dst - src`` (``Graph.shift_split``). For those edges the relaxation

    dist[u, b] <- min(dist[u, b], w(u -> u+s) + dist[u+s, b])

is a **static slice + add + min** — pure vectorized VPU work, no gather at
all. Only the uncovered leftover edges (K_left small, often 0) pay the
gather. Measured on the 96x96 bench city: 3.4x faster than the ELL
relaxation, bit-identical distances.

The shift set is static (baked into the compiled program via closure); the
weight tables are runtime arrays so the same program serves any graph with
the same shift signature.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .device_graph import JINF


def split_coverage(w_shift: np.ndarray, w_left: np.ndarray) -> float:
    """Fraction of edge slots served gather-free, from the HOST-side
    ``shift_split`` arrays (so callers can decide before paying any
    device transfer). 1.0 = no gathers in the relaxation."""
    on_shift = int((np.asarray(w_shift) < int(JINF)).sum())
    left = int((np.asarray(w_left) < int(JINF)).sum()) if w_left.size else 0
    total = on_shift + left
    return 1.0 if total == 0 else on_shift / total


class ShiftGraph:
    """Host-side bundle of ``Graph.shift_split`` outputs, device-ready.

    ``shifts`` is static (compile-time); the arrays are jit inputs.
    Coverage is computed from the host arrays at construction, before any
    device transfer.
    """

    def __init__(self, shifts, w_shift, nbr_left, w_left, n: int):
        self.shifts = tuple(int(s) for s in shifts)
        self._coverage = split_coverage(w_shift, w_left)
        self.w_shift = jnp.asarray(w_shift, jnp.int32)
        self.nbr_left = jnp.asarray(nbr_left, jnp.int32)
        self.w_left = jnp.asarray(w_left, jnp.int32)
        self.n = int(n)

    @classmethod
    def from_graph(cls, graph, max_shifts: int = 64) -> "ShiftGraph":
        shifts, w_shift, nbr_left, w_left = graph.shift_split(max_shifts)
        return cls(shifts, w_shift, nbr_left, w_left, graph.n)

    @property
    def k_left(self) -> int:
        return int(self.nbr_left.shape[1])

    def coverage(self) -> float:
        return self._coverage


@functools.lru_cache(maxsize=None)
def _dist_fn(shifts: tuple, n: int, k_left: int, max_iters: int):
    pad = max((abs(s) for s in shifts), default=0)
    limit = (n - 1) if max_iters == 0 else max_iters

    def relax(d, w_shift, nbr_left, w_left):
        dp = jnp.pad(d, ((pad, pad), (0, 0)), constant_values=JINF)
        acc = d
        for si, s in enumerate(shifts):
            sh = jax.lax.slice_in_dim(dp, pad + s, pad + s + n, axis=0)
            acc = jnp.minimum(acc,
                              jnp.minimum(w_shift[si][:, None] + sh, JINF))
        if k_left:
            via = w_left[:, :, None] + d[nbr_left, :]
            acc = jnp.minimum(acc, jnp.minimum(via, JINF).min(axis=1))
        return acc

    @jax.jit
    def dist_to_targets_shift(w_shift, nbr_left, w_left, targets):
        b = targets.shape[0]
        valid = targets >= 0
        t_safe = jnp.where(valid, targets, 0)
        dist0 = jnp.full((n, b), JINF, jnp.int32)
        dist0 = dist0.at[t_safe, jnp.arange(b)].set(
            jnp.where(valid, jnp.int32(0), JINF))

        def cond(st):
            i, d, ch = st
            return ch & (i < limit)

        def body(st):
            i, d, _ = st
            nd = relax(d, w_shift, nbr_left, w_left)
            return i + 1, nd, jnp.any(nd < d)

        # data-derived seed: varying under shard_map (a literal True has
        # replicated type and the carry check rejects it), True iff any
        # valid target row exists
        seed = jnp.any(dist0 < JINF)
        _, d, _ = jax.lax.while_loop(cond, body,
                                     (jnp.int32(0), dist0, seed))
        return d.T

    return dist_to_targets_shift


def dist_to_targets_shift(sg: ShiftGraph, targets, max_iters: int = 0):
    """int32 [B, N] of d(x → targets[b]) — gather-free relaxation.

    Distances are over **in**-shifts of each node: the recurrence relaxes
    along out-edges exactly like ``bellman_ford.dist_to_targets`` and the
    two agree bit-for-bit (tests).
    """
    fn = _dist_fn(sg.shifts, sg.n, sg.k_left, max_iters)
    return fn(sg.w_shift, sg.nbr_left, sg.w_left,
              jnp.asarray(targets, jnp.int32))


def build_fm_columns_shift(dg, sg: ShiftGraph, targets,
                           max_iters: int = 0):
    """CPD build via the shift relaxation + the shared first-move
    extraction (tie-break identical to the ELL path)."""
    from .bellman_ford import first_move_from_dist

    dist = dist_to_targets_shift(sg, targets, max_iters=max_iters)
    return first_move_from_dist(dg, jnp.asarray(targets, jnp.int32), dist)
