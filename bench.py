"""Headline benchmark: whole-scenario query throughput on the CPD oracle.

Mirrors the reference's headline workload (BASELINE.md): build the CPD for a
city-scale road network, then answer an entire scenario file of s–t queries.
The north-star target is "every query in full.scen answered in < 1 s"
(BASELINE.json): ``vs_baseline`` reports target_time / measured_time for the
scenario phase, so > 1.0 means beating the target.

The reference's own data files are absent from its snapshot, so the workload
is a deterministic synthetic city of comparable structure (two-way street
grid + arterials; see ``data/synth.py``). Sections (env-gated):

  main       96x96 city (9.2k nodes): build + walk/diff/dist campaigns
  table      pointer-doubling amortization path       (BENCH_TABLE=0 skips)
  scale      320x320 city (102,400 nodes), single chip: one full worker
             shard built with the fast-sweeping kernel, then streamed
             row-chunk serving from the on-disk index
                                                      (BENCH_SCALE=0 skips)
  weak       build-time weak scaling over a virtual 1/2/4/8-device CPU
             mesh (subprocess)                        (BENCH_WEAK=0 skips)

Roofline accounting: the walk is scalar-gather-bound, so the bench
calibrates the device's achievable gather rate with a micro-kernel of the
same shape and reports achieved vs peak (utilization) — q/s alone cannot
say whether a number is good.

Scale knobs: BENCH_WIDTH/HEIGHT, BENCH_QUERIES, BENCH_CHUNK,
BENCH_SCALE_SIDE, BENCH_SCALE_QUERIES.

Prints exactly ONE JSON line to stdout; progress goes to stderr.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _calibrate_gather(n: int, q: int, iters: int = 64):
    """Peak scalar-gather rate (elements/s) with the walk's access shape:
    a while_loop of unrolled dependent [Q]-from-[N] gathers."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    a = jnp.asarray(np.random.default_rng(0).integers(0, n, n), jnp.int32)
    idx0 = jnp.asarray(np.random.default_rng(1).integers(0, n, q), jnp.int32)

    @jax.jit
    def run(idx):
        def body(st):
            i, x = st
            for _ in range(8):
                x = a[x]                      # dependent gather chain
            return i + 1, x

        return jax.lax.while_loop(lambda st: st[0] < iters, body,
                                  (jnp.int32(0), idx))[1]

    run(idx0).block_until_ready()             # compile
    t0 = time.perf_counter()
    run(idx0).block_until_ready()
    dt = time.perf_counter() - t0
    return q * 8 * iters / dt


def _calibrate_hbm(mb: int = 512):
    """Streaming HBM bandwidth (bytes/s touched) via y = x + 1."""
    import jax
    import jax.numpy as jnp

    x = jnp.zeros(mb * (1 << 20) // 4, jnp.int32)
    f = jax.jit(lambda v: v + 1)
    f(x).block_until_ready()
    t0 = time.perf_counter()
    f(x).block_until_ready()
    dt = time.perf_counter() - t0
    return 2 * x.size * 4 / dt                 # read + write


def _native_bins():
    """Build (if needed) and locate the native CPU engine — the measured
    denominator the north-star speedups are judged against (reference
    README.md:88-95: baselines must be produced by running the pipeline,
    not copied)."""
    import shutil

    if shutil.which("g++") is None or shutil.which("make") is None:
        return None
    here = os.path.dirname(os.path.abspath(__file__))
    bindir = os.path.join(here, "native", "build", "fast", "bin")
    try:
        subprocess.run(["make", "-C", os.path.join(here, "native"), "fast",
                        "-j4"], check=True, capture_output=True,
                       timeout=300)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
        log(f"native build failed, skipping CPU baseline: {e}")
        return None
    return {n: os.path.join(bindir, n)
            for n in ("make_cpd_auto", "fifo_auto")}


def _cpu_query_campaign(bins, xy, index, scen_queries, workdir,
                        partmethod="mod", partkey=1, workerid=0,
                        maxworker=1, rounds=2):
    """Resident ``fifo_auto`` campaign over the FIFO wire; returns the
    engine's best per-round ``t_search`` seconds (same stats field the
    reference reports, process_query.py:198-213)."""
    import numpy as np

    from distributed_oracle_search_tpu.transport.wire import (
        write_query_file,
    )

    fifo = os.path.join(workdir, "cpu.fifo")
    proc = subprocess.Popen(
        [bins["fifo_auto"], "--input", xy, "--partmethod", partmethod,
         "--partkey", str(partkey), "--workerid", str(workerid),
         "--maxworker", str(maxworker), "--outdir", index,
         "--alg", "table-search", "--fifo", fifo],
        stderr=subprocess.DEVNULL)
    deadline = time.time() + 120
    while not os.path.exists(fifo):
        if time.time() > deadline:
            proc.kill()
            raise RuntimeError("fifo_auto never came up")
        time.sleep(0.1)
    qf = os.path.join(workdir, "cpu.query")
    write_query_file(qf, np.asarray(scen_queries))
    best = None
    try:
        for r in range(rounds):
            af = os.path.join(workdir, f"cpu{r}.answer")
            os.mkfifo(af)
            with open(fifo, "w") as f:
                f.write('{"itrs": 1}\n' + f"{qf} {af} -\n")
            with open(af) as f:
                line = f.readline().strip()
            os.unlink(af)
            parts = line.split(",")
            assert int(parts[6]) == len(scen_queries), \
                f"CPU campaign unfinished: {line}"
            t_search = float(parts[9])
            best = t_search if best is None else min(best, t_search)
    finally:
        with open(fifo, "w") as f:
            f.write("__DOS_STOP__\n")
        proc.wait(timeout=30)
    return best


def _weak_scaling(side: int, rows: int, chunk: int):
    """Build-time vs worker count on a virtual CPU mesh (subprocess so the
    TPU-pinned parent process cannot leak in). Same TOTAL rows each run."""
    code = f"""
import json, os, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np
from distributed_oracle_search_tpu.data import synth_city_graph
from distributed_oracle_search_tpu.models.cpd import CPDOracle
from distributed_oracle_search_tpu.parallel import DistributionController
from distributed_oracle_search_tpu.parallel.mesh import make_mesh
g = synth_city_graph({side}, {side}, seed=0)
out = {{}}
for w in (1, 2, 4, 8):
    dc = DistributionController("tpu", None, w, g.n)
    mesh = make_mesh(n_workers=w)
    o = CPDOracle(g, dc, mesh=mesh)
    o.build(chunk={chunk})                      # warm-up: compile
    o = CPDOracle(g, dc, mesh=mesh)
    t0 = time.perf_counter()
    o.build(chunk={chunk})
    jax.block_until_ready(o.fm)
    out[str(w)] = round(time.perf_counter() - t0, 3)
print(json.dumps(out))
"""
    res = subprocess.run([sys.executable, "-c", code], cwd=os.path.dirname(
        os.path.abspath(__file__)), capture_output=True, text=True,
        timeout=900)
    if res.returncode != 0:
        log(f"weak-scaling subprocess failed: {res.stderr[-500:]}")
        return {}
    return json.loads(res.stdout.strip().splitlines()[-1])


def main() -> None:
    import jax
    import numpy as np

    try:  # persistent compile cache: repeated bench runs skip XLA compiles
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/jax_bench"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # pragma: no cover - cache is best-effort
        log(f"compilation cache unavailable: {e}")

    from distributed_oracle_search_tpu.data import (
        synth_city_graph, synth_scenario, synth_diff, write_xy,
    )
    from distributed_oracle_search_tpu.models.cpd import CPDOracle
    from distributed_oracle_search_tpu.parallel import DistributionController
    from distributed_oracle_search_tpu.parallel.mesh import make_mesh
    from distributed_oracle_search_tpu.utils import Timer

    width = int(os.environ.get("BENCH_WIDTH", 96))
    height = int(os.environ.get("BENCH_HEIGHT", 96))
    n_queries = int(os.environ.get("BENCH_QUERIES", 50_000))
    chunk = int(os.environ.get("BENCH_CHUNK", 512))

    devices = jax.devices()
    log(f"devices: {devices}")
    n_workers = len(devices)

    with Timer() as t_gen:
        g = synth_city_graph(width, height, seed=0)
        queries = synth_scenario(g.n, n_queries, seed=1)
    log(f"graph n={g.n} m={g.m} K={g.max_out_degree}; "
        f"{n_queries} queries; gen {t_gen}")

    dc = DistributionController("tpu", None, n_workers, g.n)
    mesh = make_mesh(n_workers=n_workers)
    oracle = CPDOracle(g, dc, mesh=mesh)

    # warm-up build: compiles the relaxation program (the persistent
    # compile cache usually absorbs this, but a cache miss would smear
    # ~40s of XLA compile into the timed build)
    with Timer() as t_bwarm:
        CPDOracle(g, dc, mesh=mesh).build(chunk=chunk, store_dists=True)
    log(f"build warm-up (compile): {t_bwarm}")
    with Timer() as t_build:
        oracle.build(chunk=chunk, store_dists=True)
        jax.block_until_ready(oracle.fm)
    rows_per_s = g.n / t_build.interval
    log(f"CPD build: {t_build} ({rows_per_s:,.0f} target rows/s, "
        f"{g.n * g.n / t_build.interval / 1e9:.2f} G entries/s)")

    # congestion diff for the perturbed round (reference: one round/diff)
    dsrc, ddst, dw = synth_diff(g, frac=0.1, seed=2)
    w_diff = g.weights_with_diff((dsrc, ddst, dw))

    bench_table = os.environ.get("BENCH_TABLE", "1") != "0"

    # warm-up at the full scenario shape: compiles each query program once,
    # like the reference's resident fifo_auto loading before the campaign.
    # Timed PER PROGRAM so compile regressions are attributable; the table
    # section warms itself up later — its large prepare program used to
    # run here and skewed both this number and the walk timings after it
    warmups = {}
    with Timer() as t_compile:
        with Timer() as tw:
            oracle.query(queries)
        warmups["walk"] = round(tw.interval, 2)
        with Timer() as tw:
            oracle.query(queries, w_query=w_diff)
        warmups["walk_diff"] = round(tw.interval, 2)
        with Timer() as tw:
            oracle.query_dist(queries)
        warmups["dist"] = round(tw.interval, 2)
    log(f"query warm-up (compile): {t_compile} "
        + " ".join(f"{k}={v}s" for k, v in warmups.items()))

    def best_of(fn, reps: int = 3):
        """Best-of-N timing: single-shot numbers on a tunneled device link
        jitter by 10-20%; the minimum is the reproducible figure."""
        out = None
        best = None
        for _ in range(reps):
            with Timer() as tt:
                out = fn()
            if best is None or tt.interval < best.interval:
                best = tt
        return out, best

    (cost, plen, finished), t_scen = best_of(lambda: oracle.query(queries))
    n_fin = int(finished.sum())
    qps = n_queries / t_scen.interval
    mean_plen = float(plen.mean())
    log(f"walk free-flow: {n_queries} in {t_scen} -> {qps:,.0f} q/s; "
        f"finished {n_fin}/{n_queries}, mean plen {mean_plen:.1f}")
    assert n_fin == n_queries, "benchmark correctness gate failed"

    (cost_d, plen_d, fin_d), t_diff = best_of(
        lambda: oracle.query(queries, w_query=w_diff))
    assert int(fin_d.sum()) == n_queries
    assert (cost_d >= cost).all(), "diffed costs must dominate free flow"
    log(f"walk diffed:   {n_queries} in {t_diff} -> "
        f"{n_queries / t_diff.interval:,.0f} q/s")

    (cost_g, fin_g), t_dist = best_of(lambda: oracle.query_dist(queries))
    assert (cost_g == cost).all(), "dist fast path must match the walk"
    log(f"dist gather:   {n_queries} in {t_dist} -> "
        f"{n_queries / t_dist.interval:,.0f} q/s")

    # ---- roofline: the walk does 2 scalar gathers per step per query
    # (fm slot + the packed (next-node, weight) pair); compare achieved
    # rate to a calibrated dependent-gather micro-kernel of the same
    # shape
    from distributed_oracle_search_tpu.ops.table_search import pick_buckets

    peak_gather = _calibrate_gather(g.n, n_queries)
    hbm_bw = _calibrate_hbm()
    # device-kernel time WITHOUT the host round trip: the end-to-end walk
    # pays a fixed ~90 ms device->host fetch on this tunneled link, which
    # is transport, not kernel — utilization is a kernel property
    from distributed_oracle_search_tpu.parallel.sharded import (
        query_sharded,
    )
    ra, sa, ta, va, _ = oracle.route(queries)
    _, t_kern = best_of(lambda: jax.block_until_ready(query_sharded(
        oracle.dg, oracle.fm, ra, sa, ta, va, oracle.dg.w_pad,
        oracle.mesh)))
    # the bucketed walk (ops.table_search n_buckets) runs each bucket to
    # its OWN max length: reconstruct issued gathers from route()'s
    # actual per-device layout (each (data, worker) plane is an
    # est-sorted, separately padded [qmax] column). Utilization compares
    # the CRITICAL-PATH device (max lanes) to the single-device peak.
    _, _, _, valid_dwq, (act, sd, sw, sq) = oracle.route(queries)
    dgrid, wgrid, qmax = valid_dwq.shape
    plen_dwq = np.zeros((dgrid, wgrid, qmax))
    plen_dwq[sd[act], sw[act], sq[act]] = np.asarray(plen)[act]
    b = pick_buckets(qmax, 0)
    qb = qmax // b
    unroll = 8
    per_bucket_max = plen_dwq.reshape(dgrid, wgrid, b, qb).max(axis=3)
    lanes_dev = (np.ceil(per_bucket_max / unroll) * unroll).sum(
        axis=2) * qb                                  # [D, W] per device
    lanes_issued = float(lanes_dev.max())
    gathers_per_step = 2          # fm slot + packed (next, weight) pair
    achieved_gather = ((n_queries / (dgrid * wgrid)) * mean_plen
                       * gathers_per_step / t_kern.interval)
    issued_gather = lanes_issued * gathers_per_step / t_kern.interval
    log(f"roofline: kernel {t_kern.interval:.3f}s, peak gather "
        f"{peak_gather / 1e6:,.0f} M elem/s, "
        f"useful {achieved_gather / 1e6:,.0f} "
        f"({achieved_gather / peak_gather:.0%}), issued "
        f"{issued_gather / 1e6:,.0f} ({issued_gather / peak_gather:.0%}); "
        f"HBM {hbm_bw / 1e9:,.0f} GB/s")

    # ---- measured CPU denominator: the SAME graph + scenario through the
    # native OpenMP engine (full build + resident fifo_auto campaign over
    # the real FIFO wire). This is the reference pipeline's stand-in; the
    # north-star "≥10x build" (BASELINE.md) is judged against it.
    # BENCH_CPU=0 skips.
    cpu_stats = {}
    if os.environ.get("BENCH_CPU", "1") != "0":
        bins = _native_bins()
        if bins is None:
            log("CPU baseline skipped: no native toolchain")
        else:
            import shutil
            import tempfile

            cdir = tempfile.mkdtemp(prefix="dos-cpu-")
            try:
                xy = os.path.join(cdir, "city.xy")
                cidx = os.path.join(cdir, "index")
                write_xy(xy, g.xs, g.ys, g.src, g.dst, g.w)
                with Timer() as t_cpu_b:
                    subprocess.run(
                        [bins["make_cpd_auto"], "--input", xy,
                         "--partmethod", "mod", "--partkey", "1",
                         "--workerid", "0", "--maxworker", "1",
                         "--outdir", cidx],
                        check=True, capture_output=True)
                t_cpu_q = _cpu_query_campaign(bins, xy, cidx, queries,
                                              cdir)
                cores = os.cpu_count() or 1
                cpu_qps = n_queries / t_cpu_q
                build_speedup = t_cpu_b.interval / t_build.interval
                query_speedup = t_cpu_q / t_scen.interval
                log(f"CPU baseline ({cores} core(s)): build {t_cpu_b} "
                    f"(tpu {build_speedup:.1f}x), campaign t_search "
                    f"{t_cpu_q:.3f}s -> {cpu_qps:,.0f} q/s "
                    f"(tpu walk {query_speedup:.2f}x, dist "
                    f"{t_cpu_q / t_dist.interval:.2f}x)")
                cpu_stats = {
                    "cpu_cores": cores,
                    "cpu_build_seconds": round(t_cpu_b.interval, 2),
                    "cpu_queries_per_sec": round(cpu_qps, 1),
                    "tpu_build_speedup": round(build_speedup, 2),
                    "tpu_query_speedup": round(query_speedup, 3),
                    "tpu_dist_speedup": round(
                        t_cpu_q / t_dist.interval, 3),
                }
            finally:
                shutil.rmtree(cdir, ignore_errors=True)

    # pointer-doubling amortization path: whole-shard cost tables for the
    # DIFFED weights, then gather-speed answers. Costs O(R*N*log L)
    # gathers up front — the >1M-query trade (BASELINE.md configs[4]).
    # BENCH_TABLE=0 skips it for quick runs.
    table_stats = {}
    if bench_table:
        # warm-up: compile the prepare/lookup programs at shape on the
        # free-flow weights, so the timed run below is steady-state (and
        # the compile cost is attributable here, not smeared into it)
        with Timer() as t_tabc:
            warm = oracle.prepare_weights(None)
            # full scenario shape: a different batch size would compile a
            # different lookup program and the timed run would pay it
            oracle.query_table(warm, queries)
            jax.block_until_ready(warm[0])
            del warm
        log(f"table warm-up (compile): {t_tabc}")
        with Timer() as t_prep:
            tables = oracle.prepare_weights(w_diff)
            jax.block_until_ready(tables[0])
        (cost_t, plen_t, fin_t), t_tab = best_of(
            lambda: oracle.query_table(tables, queries))
        assert (cost_t == cost_d).all(), \
            "table path must match the diff walk"
        assert (plen_t == plen_d).all() and (fin_t == fin_d).all()
        log(f"diff tables:   prepare {t_prep}; {n_queries} in {t_tab} -> "
            f"{n_queries / t_tab.interval:,.0f} q/s")
        table_stats = {
            "table_prepare_seconds": round(t_prep.interval, 3),
            "table_queries_per_sec": round(n_queries / t_tab.interval, 1),
        }
        del tables

    # ---- scale section: 102k-node city, single chip. One complete worker
    # shard (div/8) built with the fast-sweeping kernel and served
    # STREAMED from the on-disk block files — the serving mode for indexes
    # that exceed HBM (full fm at this scale: N^2 = 10.5 GB single-shard).
    scale_stats = {}
    if os.environ.get("BENCH_SCALE", "1") != "0":
        import shutil
        import tempfile

        from distributed_oracle_search_tpu.models.cpd import (
            build_worker_shard, write_index_manifest,
        )
        from distributed_oracle_search_tpu.models.streamed import (
            StreamedCPDOracle,
        )

        side = int(os.environ.get("BENCH_SCALE_SIDE", 320))
        sq = int(os.environ.get("BENCH_SCALE_QUERIES", 20_000))
        g2 = synth_city_graph(side, side, seed=0)
        w_scale = 8
        per_w = -(-g2.n // w_scale)
        dc2 = DistributionController("div", per_w, w_scale, g2.n)
        outdir = tempfile.mkdtemp(prefix="dos-scale-")
        try:
            log(f"scale: n={g2.n} building worker 0 shard "
                f"({dc2.n_owned(0)} rows, sweep kernel)...")
            # warm-up: compile the sweep program at the build chunk shape
            # (persistent-cached across runs) so the timed build is
            # steady-state like every other section
            from distributed_oracle_search_tpu.models.cpd import (
                pick_build_kernel,
            )
            from distributed_oracle_search_tpu.ops import DeviceGraph
            from distributed_oracle_search_tpu.ops.grid_sweep import (
                build_fm_columns_sweep,
            )
            _, gg2 = pick_build_kernel(g2, "sweep")
            dg2 = DeviceGraph.from_graph(g2)
            sc_chunk = int(os.environ.get("BENCH_SCALE_CHUNK", 1024))
            jax.block_until_ready(build_fm_columns_sweep(
                dg2, gg2, np.arange(sc_chunk, dtype=np.int32)))
            # chunk=1024: the sweep kernel's while-body holds several
            # skewed [CA, H, B] buffers; 1024 rows (~5 GB working set at
            # this graph size) measured 20% faster per row than 512 and
            # fits a 16 GB chip with the pipelined double-block drain
            with Timer() as t_b2:
                build_worker_shard(g2, dc2, 0, outdir, chunk=sc_chunk,
                                   method="sweep")
            rows0 = dc2.n_owned(0)
            rps2 = rows0 / t_b2.interval
            full_est = g2.n / rps2
            write_index_manifest(outdir, dc2, workers=[0])
            log(f"scale build: {rows0} rows in {t_b2} -> {rps2:,.0f} "
                f"rows/s ({rps2 * g2.n / 1e9:.2f} G entries/s), full-index "
                f"extrapolation {full_est:,.0f}s")

            rng = np.random.default_rng(3)
            q2 = np.stack([rng.integers(0, g2.n, sq),
                           rng.integers(0, rows0, sq)], axis=1)
            st = StreamedCPDOracle(g2, dc2, outdir, row_chunk=4096)
            st.query(q2[:256])                 # warm-up: compile
            with Timer() as t_q2:
                c2, p2, f2 = st.query(q2)
            assert bool(f2.all()), "scale campaign left unfinished queries"
            sqps = sq / t_q2.interval
            mbps = st.last_stats["bytes_streamed"] / t_q2.interval / 1e6
            log(f"scale streamed: {sq} queries in {t_q2} -> {sqps:,.0f} "
                f"q/s; streamed {st.last_stats['bytes_streamed'] / 1e6:,.0f}"
                f" MB ({mbps:,.0f} MB/s incl. walk)")
            scale_stats = {
                "scale_nodes": g2.n,
                "scale_build_rows": rows0,
                "scale_build_seconds": round(t_b2.interval, 2),
                "scale_build_rows_per_sec": round(rps2, 1),
                "scale_full_build_est_seconds": round(full_est, 1),
                "scale_stream_queries_per_sec": round(sqps, 1),
                "scale_stream_mb": round(
                    st.last_stats["bytes_streamed"] / 1e6, 1),
            }

            # resident serving of the SAME shard: 1.3 GB int8 fits HBM —
            # this is one chip of the real multi-chip deployment (each
            # chip holds its worker's shard resident; streaming is for
            # the regime where even one shard exceeds HBM)
            import jax.numpy as jnp

            from distributed_oracle_search_tpu.ops.table_search import (
                table_search_batch,
            )

            blocks = sorted(f for f in os.listdir(outdir)
                            if f.startswith("cpd-w00000"))
            fm0 = jnp.asarray(np.concatenate(
                [np.load(os.path.join(outdir, f)) for f in blocks]))
            # div partition: worker 0's owned row index == target node id
            est2 = (np.abs(g2.xs[q2[:, 0]] - g2.xs[q2[:, 1]])
                    + np.abs(g2.ys[q2[:, 0]] - g2.ys[q2[:, 1]]))
            order2 = np.argsort(est2, kind="stable")
            qpad = 1 << (sq - 1).bit_length()
            rr = np.zeros(qpad, np.int32)
            ss = np.zeros(qpad, np.int32)
            tt2 = np.zeros(qpad, np.int32)
            vv = np.zeros(qpad, bool)
            rr[:sq] = q2[order2, 1]
            ss[:sq] = q2[order2, 0]
            tt2[:sq] = q2[order2, 1]
            vv[:sq] = True

            def resident():
                return jax.block_until_ready(table_search_batch(
                    dg2, fm0, rr, ss, tt2, dg2.w_pad, valid=vv))
            (cr, pr, fr), t_res = best_of(resident)
            assert bool(np.asarray(fr)[:sq].all())
            assert (np.asarray(cr)[np.argsort(order2)] == c2).all(), \
                "resident shard serve must match streamed answers"
            rqps = sq / t_res.interval
            log(f"scale resident: {sq} queries in {t_res} -> "
                f"{rqps:,.0f} q/s (worker-0 shard, "
                f"{fm0.nbytes / 1e9:.1f} GB on HBM)")
            scale_stats["scale_resident_queries_per_sec"] = round(rqps, 1)
            del fm0

            # CPU at the same scale (BENCH_CPU=0 skips): build rate from
            # a 512-row sub-worker (div/512 — a full worker shard would
            # take minutes), serve from the SAME on-disk index the sweep
            # kernel just wrote (block files are builder-agnostic,
            # tests/test_native.py block parity)
            if os.environ.get("BENCH_CPU", "1") != "0":
                bins = _native_bins()
                if bins is not None:
                    xy2 = os.path.join(outdir, "scale.xy")
                    write_xy(xy2, g2.xs, g2.ys, g2.src, g2.dst, g2.w)
                    sub_rows = 512
                    with Timer() as t_cb2:
                        subprocess.run(
                            [bins["make_cpd_auto"], "--input", xy2,
                             "--partmethod", "div",
                             "--partkey", str(sub_rows),
                             "--workerid", "0",
                             "--maxworker",
                             str(-(-g2.n // sub_rows)),
                             "--outdir",
                             os.path.join(outdir, "cpuidx")],
                            check=True, capture_output=True)
                    cpu_rps2 = sub_rows / t_cb2.interval
                    t_cpu_q2 = _cpu_query_campaign(
                        bins, xy2, outdir, q2, outdir,
                        partmethod="div", partkey=per_w, workerid=0,
                        maxworker=w_scale)
                    cpu_qps2 = sq / t_cpu_q2
                    log(f"scale CPU: build {cpu_rps2:,.0f} rows/s "
                        f"(tpu {rps2 / cpu_rps2:.1f}x), campaign "
                        f"t_search {t_cpu_q2:.3f}s -> {cpu_qps2:,.0f} "
                        f"q/s (tpu streamed {t_cpu_q2 / t_q2.interval:.2f}"
                        f"x)")
                    scale_stats.update({
                        "scale_cpu_build_rows_per_sec": round(cpu_rps2, 1),
                        "scale_cpu_queries_per_sec": round(cpu_qps2, 1),
                        "scale_tpu_build_speedup": round(
                            rps2 / cpu_rps2, 2),
                        "scale_tpu_stream_speedup": round(
                            t_cpu_q2 / t_q2.interval, 3),
                        "scale_tpu_resident_speedup": round(
                            t_cpu_q2 / t_res.interval, 3),
                    })
        finally:
            shutil.rmtree(outdir, ignore_errors=True)

    # ---- road section: non-grid, degree-skewed 264k-node network (the
    # DIMACS stand-in, BASELINE.md configs[5]) — the regime where the
    # grid/shift build gates MUST fall back gracefully. Build via the ELL
    # fallback on TPU vs per-source Dijkstra on CPU; serve streamed and
    # resident from the same index. BENCH_ROAD=0 skips.
    road_stats = {}
    if os.environ.get("BENCH_ROAD", "1") != "0":
        import shutil
        import tempfile

        import jax.numpy as jnp

        from distributed_oracle_search_tpu.data import synth_road_network
        from distributed_oracle_search_tpu.models.cpd import (
            pick_build_kernel, write_index_manifest,
        )
        from distributed_oracle_search_tpu.models.streamed import (
            StreamedCPDOracle,
        )
        from distributed_oracle_search_tpu.ops import DeviceGraph
        from distributed_oracle_search_tpu.ops.shift_relax import (
            split_coverage,
        )
        from distributed_oracle_search_tpu.ops.table_search import (
            table_search_batch,
        )

        rn = int(os.environ.get("BENCH_ROAD_NODES", 264_000))
        g3 = synth_road_network(rn, seed=0)
        _, ws_raw, _, wl_raw = g3.shift_split()
        cov_raw = split_coverage(ws_raw, wl_raw)
        with Timer() as t_rcm:
            g3 = g3.reorder(g3.rcm_order())
        _, ws_rcm, _, wl_rcm = g3.shift_split()
        cov_rcm = split_coverage(ws_rcm, wl_rcm)
        kind3, st3k = pick_build_kernel(g3, "auto")
        log(f"road: n={g3.n} m={g3.m} K={g3.max_out_degree}; rcm reorder "
            f"{t_rcm}; shift coverage {cov_raw:.1%} -> {cov_rcm:.1%}; "
            f"auto build kernel = {kind3} (grid/shift gates fell back "
            f"as designed)")

        sub = 512                       # rows per serving sub-worker
        mw3 = -(-g3.n // sub)
        dc3 = DistributionController("div", sub, mw3, g3.n)
        out3 = tempfile.mkdtemp(prefix="dos-road-")
        try:
            # TPU build via the auto-picked kernel (delta-stepping
            # frontier queue on the RCM-ordered road graph), 512 timed
            # rows — the same row count the CPU build below is timed on
            trows = 512
            dg3 = DeviceGraph.from_graph(g3)
            if kind3 == "frontier":
                from distributed_oracle_search_tpu.ops.frontier_relax \
                    import build_fm_columns_frontier
                build3 = lambda t: build_fm_columns_frontier(  # noqa: E731
                    dg3, st3k, t)
            elif kind3 == "ellsplit":
                from distributed_oracle_search_tpu.ops.ell_split import (
                    build_fm_columns_ellsplit,
                )
                build3 = lambda t: build_fm_columns_ellsplit(  # noqa: E731
                    dg3, st3k, t)
            elif kind3 == "shift":
                from distributed_oracle_search_tpu.ops.shift_relax import (
                    build_fm_columns_shift,
                )
                build3 = lambda t: build_fm_columns_shift(  # noqa: E731
                    dg3, st3k, t)
            elif kind3 == "sweep":
                from distributed_oracle_search_tpu.ops.grid_sweep import (
                    build_fm_columns_sweep,
                )
                build3 = lambda t: build_fm_columns_sweep(  # noqa: E731
                    dg3, st3k, t)
            else:
                from distributed_oracle_search_tpu.ops import (
                    build_fm_columns,
                )
                build3 = lambda t: build_fm_columns(  # noqa: E731
                    dg3, jnp.asarray(t))
            tgt64 = np.arange(trows, dtype=np.int32)
            jax.block_until_ready(build3(tgt64))             # compile
            with Timer() as t_b3:
                fm64 = np.asarray(build3(tgt64))             # [512, N]
            tpu_rps3 = trows / t_b3.interval
            log(f"road TPU build ({kind3}): {trows} rows in {t_b3} -> "
                f"{tpu_rps3:,.1f} rows/s")

            bins = (_native_bins()
                    if os.environ.get("BENCH_CPU", "1") != "0" else None)
            if bins is not None:
                xy3 = os.path.join(out3, "road.xy")
                write_xy(xy3, g3.xs, g3.ys, g3.src, g3.dst, g3.w)
                with Timer() as t_cb3:
                    subprocess.run(
                        [bins["make_cpd_auto"], "--input", xy3,
                         "--partmethod", "div", "--partkey", str(sub),
                         "--workerid", "0", "--maxworker", str(mw3),
                         "--outdir", out3],
                        check=True, capture_output=True)
                cpu_rps3 = sub / t_cb3.interval
                # correctness gate: ELL build and native Dijkstra must
                # produce bit-identical first moves on this graph too
                blk0 = np.load(os.path.join(
                    out3, "cpd-w00000-b00000.npy"))
                assert (blk0[:trows] == fm64).all(), \
                    "road: TPU ELL fm rows != native Dijkstra rows"
                log(f"road CPU build: {sub} rows in {t_cb3} -> "
                    f"{cpu_rps3:,.1f} rows/s (tpu "
                    f"{tpu_rps3 / cpu_rps3:.2f}x); fm parity ok")

                write_index_manifest(out3, dc3, workers=[0])
                rng = np.random.default_rng(5)
                rq = int(os.environ.get("BENCH_ROAD_QUERIES", 20_000))
                q3 = np.stack([rng.integers(0, g3.n, rq),
                               rng.integers(0, sub, rq)], axis=1)
                st3 = StreamedCPDOracle(g3, dc3, out3, row_chunk=512)
                st3.query(q3[:256])
                with Timer() as t_q3:
                    c3, p3, f3 = st3.query(q3)
                assert bool(f3.all())
                log(f"road streamed: {rq} in {t_q3} -> "
                    f"{rq / t_q3.interval:,.0f} q/s")

                # resident worker-0 shard (135 MB) — the per-chip unit
                fm0r = jnp.asarray(blk0)
                est3 = (np.abs(g3.xs[q3[:, 0]] - g3.xs[q3[:, 1]])
                        + np.abs(g3.ys[q3[:, 0]] - g3.ys[q3[:, 1]]))
                o3 = np.argsort(est3, kind="stable")
                qp3 = 1 << (rq - 1).bit_length()
                rr3 = np.zeros(qp3, np.int32)
                ss3 = np.zeros(qp3, np.int32)
                tt3 = np.zeros(qp3, np.int32)
                vv3 = np.zeros(qp3, bool)
                rr3[:rq] = q3[o3, 1]
                ss3[:rq] = q3[o3, 0]
                tt3[:rq] = q3[o3, 1]
                vv3[:rq] = True
                (cr3, pr3, fr3), t_r3 = best_of(
                    lambda: jax.block_until_ready(table_search_batch(
                        dg3, fm0r, rr3, ss3, tt3, dg3.w_pad, valid=vv3)))
                assert bool(np.asarray(fr3)[:rq].all())
                assert (np.asarray(cr3)[np.argsort(o3)] == c3).all()
                rqps3 = rq / t_r3.interval
                t_cq3 = _cpu_query_campaign(
                    bins, xy3, out3, q3, out3, partmethod="div",
                    partkey=sub, workerid=0, maxworker=mw3)
                log(f"road resident: {rq} in {t_r3} -> {rqps3:,.0f} q/s; "
                    f"CPU campaign {t_cq3:.3f}s -> "
                    f"{rq / t_cq3:,.0f} q/s (tpu resident "
                    f"{t_cq3 / t_r3.interval:.2f}x)")
                road_stats = {
                    "road_nodes": g3.n,
                    "road_edges": g3.m,
                    "road_shift_coverage_raw": round(cov_raw, 4),
                    "road_shift_coverage_rcm": round(cov_rcm, 4),
                    "road_build_kernel": kind3,
                    "road_tpu_build_rows_per_sec": round(tpu_rps3, 2),
                    "road_cpu_build_rows_per_sec": round(cpu_rps3, 2),
                    "road_stream_queries_per_sec": round(
                        rq / t_q3.interval, 1),
                    "road_resident_queries_per_sec": round(rqps3, 1),
                    "road_cpu_queries_per_sec": round(rq / t_cq3, 1),
                    "road_tpu_resident_speedup": round(
                        t_cq3 / t_r3.interval, 3),
                }
        finally:
            shutil.rmtree(out3, ignore_errors=True)

    # ---- weak scaling: same total rows over 1/2/4/8 virtual CPU devices
    weak_stats = {}
    if os.environ.get("BENCH_WEAK", "1") != "0":
        log("weak scaling (virtual CPU mesh subprocess)...")
        weak = _weak_scaling(side=64, rows=4096, chunk=512)
        if weak:
            base = weak.get("1")
            log("weak scaling build seconds: " + ", ".join(
                f"W={w}: {s}s (x{base / s:.2f})" for w, s in weak.items()))
            weak_stats = {"weak_scaling_build_seconds": weak}

    target_time = 1.0  # north star: whole scenario < 1 s (BASELINE.json)
    print(json.dumps({
        "metric": "scenario_queries_per_sec",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(target_time / t_scen.interval, 3),
        "detail": {
            "graph_nodes": g.n,
            "graph_edges": g.m,
            "n_queries": n_queries,
            "scenario_seconds": round(t_scen.interval, 4),
            "warmup_seconds": warmups,
            "diff_queries_per_sec": round(n_queries / t_diff.interval, 1),
            "dist_queries_per_sec": round(n_queries / t_dist.interval, 1),
            **cpu_stats,
            **table_stats,
            "cpd_build_seconds": round(t_build.interval, 2),
            "cpd_rows_per_sec": round(rows_per_s, 1),
            "roofline": {
                "kernel_seconds": round(t_kern.interval, 4),
                "peak_gather_meps": round(peak_gather / 1e6, 1),
                "walk_useful_gather_meps": round(achieved_gather / 1e6, 1),
                "walk_issued_gather_meps": round(issued_gather / 1e6, 1),
                "walk_gather_utilization": round(
                    issued_gather / peak_gather, 3),
                "hbm_stream_gbps": round(hbm_bw / 1e9, 1),
            },
            **scale_stats,
            **road_stats,
            **weak_stats,
            "devices": len(devices),
            "platform": devices[0].platform,
        },
    }))


if __name__ == "__main__":
    main()
