"""Headline benchmark: whole-scenario query throughput on the CPD oracle.

Mirrors the reference's headline workload (BASELINE.md): build the CPD for a
city-scale road network, then answer an entire scenario file of s–t queries.
The north-star target is "every query in full.scen answered in < 1 s"
(BASELINE.json): ``vs_baseline`` reports target_time / measured_time for the
scenario phase, so > 1.0 means beating the target.

The reference's own data files are absent from its snapshot, so the workload
is a deterministic synthetic city of comparable structure (two-way street
grid + arterials; see ``data/synth.py``). Sections (env-gated):

  main       96x96 city (9.2k nodes): build + walk/diff/dist campaigns,
             bulk-dist round, native astar/ch + device A* family rates
  table      pointer-doubling amortization path, measured break-even
                                                      (BENCH_TABLE=0 skips)
  scale      320x320 city (102,400 nodes), single chip: one full worker
             shard built with the fast-sweeping kernel, then streamed
             row-chunk serving from the on-disk index — cold round plus
             the cache-warm steady state              (BENCH_SCALE=0 skips)
  road       264k-node non-grid network: frontier build vs CPU Dijkstra,
             streamed/resident serving, free-flow AND congestion-diff
             rounds                                   (BENCH_ROAD=0 skips)
  compressed RLE/pack4 compressed-RESIDENT shard on the road rows
             (DOS_CPD_RESIDENT, models.resident): resident-bytes ratio,
             decompress-at-use walk q/s vs the raw-resident walk, and
             the per-batch decompress overhead — rides inside the road
             section                            (BENCH_COMPRESSED=0 skips)
  weak       build-time scaling over a virtual 1/2/4/8-device CPU mesh
             (subprocess), decomposed into mesh wall-clock vs per-shard
             single-device time, plus shard strong scaling on the real
             chip                                     (BENCH_WEAK=0 skips)
  serve      online serving frontend (serving/): closed-loop capacity,
             then an open-loop Poisson drill at a fraction of measured
             capacity — q/s, p50/p95/p99 latency, zipf cache hit rate,
             mean micro-batch fill                   (BENCH_SERVE=0 skips)
  gateway    rush hour on the gateway tier (gateway/): 2 binary-protocol
             frontend replicas over one worker vs the single-head line
             protocol — aggregate q/s, per-frontend fairness, fleet
             L1+L2 cache hit rate, answer parity  (BENCH_GATEWAY=0 skips)
  replication  R=2 failover drill — q/s + p99 with and without one
             killed primary (breaker forced open), plus hedge win rate
             under an injected primary delay          (BENCH_REPL=0 skips)
  reshard    elastic-membership drill — serve q/s + p99 steady vs
             through a LIVE worker join (dual-read migration window,
             epoch bump committed mid-load)        (BENCH_RESHARD=0 skips)
  traffic    live congestion plane — zipf hotspot pool served through a
             rush-hour segment replay swapping diff epochs under the
             running frontend: live-swap q/s, swap-stall p99, scoped
             cache-invalidation hit rate          (BENCH_TRAFFIC=0 skips)

All speedups are against a MEASURED native-engine run on this host's
cpu_cores core(s); *_parity_cores fields give the OpenMP core count a
linearly-scaling CPU host would need to match the TPU figure.

Roofline accounting: the walk is scalar-gather-bound, so the bench
calibrates the device's achievable gather rate with a micro-kernel of the
same shape and reports achieved vs peak (utilization) — q/s alone cannot
say whether a number is good.

Scale knobs: BENCH_WIDTH/HEIGHT, BENCH_QUERIES, BENCH_CHUNK,
BENCH_SCALE_SIDE, BENCH_SCALE_QUERIES.

Output contract (the driver captures only the LAST ~2000 stdout chars and
parses the final line as JSON — r04's single fat line outgrew that window
and the record became unparseable): stdout carries exactly ONE COMPACT
JSON line (top-line metric + headline fields, size-asserted well under
the window); the full per-section detail goes to ``BENCH_DETAIL.json``
next to this file and to stderr. Progress goes to stderr.

Every long timed section runs under a stall guard (``robust_time``): the
shared tunneled device has been observed to stall a single execution >20x
(383 s for a true ~17 s program), so single-shot timers are never trusted
— each section is best-of-2 with further retries while the best reading
still exceeds a known-good band from prior record captures.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def robust_time(fn, reset=None, reps: int = 2, band_s: float | None = None,
                max_reps: int = 4, label: str = "", drop_prev: bool = False):
    """Best-of-N wall-clock with stall escalation: run ``fn`` ``reps``
    times (calling ``reset`` between reps — builds resume from block
    files, so a rerun without reset would measure a no-op) and keep the
    fastest time. If a known-good ``band_s`` (from prior record captures,
    generously padded) is given and even the BEST reading exceeds it,
    keep retrying up to ``max_reps`` total — the device is stalling and
    one more reading is the only way to tell a stall from a real
    regression. ``drop_prev`` frees the held result before each rerun
    (two live copies of a device-resident result would double peak HBM);
    results here are deterministic, so the LAST run's result with the
    BEST run's time is still a faithful pair.
    Returns ``(result, best_seconds)``."""
    best = None
    out = None
    runs = 0
    while True:
        if runs:
            if drop_prev:
                out = None
            if reset is not None:
                reset()
        t0 = time.perf_counter()
        res = fn()
        dt = time.perf_counter() - t0
        runs += 1
        if drop_prev:
            out, best = res, (dt if best is None else min(best, dt))
        elif best is None or dt < best:
            best, out = dt, res
        if runs >= reps and (band_s is None or best <= band_s
                             or runs >= max_reps):
            if band_s is not None and best > band_s:
                log(f"robust_time[{label}]: best {best:.1f}s still above "
                    f"band {band_s:.1f}s after {runs} reps — reporting "
                    "it, but treat as possibly stalled")
            return out, best


def _calibrate_gather(n: int, q: int, iters: int = 64):
    """Peak scalar-gather rate (elements/s) with the walk's access shape:
    a while_loop of unrolled dependent [Q]-from-[N] gathers."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    a = jnp.asarray(np.random.default_rng(0).integers(0, n, n), jnp.int32)
    idx0 = jnp.asarray(np.random.default_rng(1).integers(0, n, q), jnp.int32)

    @jax.jit
    def run(idx):
        def body(st):
            i, x = st
            for _ in range(8):
                x = a[x]                      # dependent gather chain
            return i + 1, x

        return jax.lax.while_loop(lambda st: st[0] < iters, body,
                                  (jnp.int32(0), idx))[1]

    run(idx0).block_until_ready()             # compile
    t0 = time.perf_counter()
    run(idx0).block_until_ready()
    dt = time.perf_counter() - t0
    return q * 8 * iters / dt


def _calibrate_hbm(mb: int = 512):
    """Streaming HBM bandwidth (bytes/s touched) via y = x + 1."""
    import jax
    import jax.numpy as jnp

    x = jnp.zeros(mb * (1 << 20) // 4, jnp.int32)
    f = jax.jit(lambda v: v + 1)
    f(x).block_until_ready()
    t0 = time.perf_counter()
    f(x).block_until_ready()
    dt = time.perf_counter() - t0
    return 2 * x.size * 4 / dt                 # read + write


def _native_bins():
    """Build (if needed) and locate the native CPU engine — the measured
    denominator the north-star speedups are judged against (reference
    README.md:88-95: baselines must be produced by running the pipeline,
    not copied)."""
    if shutil.which("g++") is None or shutil.which("make") is None:
        return None
    here = os.path.dirname(os.path.abspath(__file__))
    bindir = os.path.join(here, "native", "build", "fast", "bin")
    try:
        subprocess.run(["make", "-C", os.path.join(here, "native"), "fast",
                        "-j4"], check=True, capture_output=True,
                       timeout=300)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
        log(f"native build failed, skipping CPU baseline: {e}")
        return None
    return {n: os.path.join(bindir, n)
            for n in ("make_cpd_auto", "fifo_auto")}


def _cpu_query_campaign(bins, xy, index, scen_queries, workdir,
                        partmethod="mod", partkey=1, workerid=0,
                        maxworker=1, rounds=2, alg="table-search",
                        difffile="-"):
    """Resident ``fifo_auto`` campaign over the FIFO wire; returns the
    engine's best per-round ``t_search`` seconds (same stats field the
    reference reports, process_query.py:198-213). ``alg`` selects the
    engine family (table-search / astar / ch); ``difffile`` runs the
    round on a congestion diff, like the reference's one-round-per-diff
    campaign loop (process_query.py:178)."""
    import numpy as np

    from distributed_oracle_search_tpu.transport.wire import (
        write_query_file,
    )

    fifo = os.path.join(workdir, f"cpu-{alg}.fifo")
    proc = subprocess.Popen(
        [bins["fifo_auto"], "--input", xy, "--partmethod", partmethod,
         "--partkey", str(partkey), "--workerid", str(workerid),
         "--maxworker", str(maxworker), "--outdir", index,
         "--alg", alg, "--fifo", fifo],
        stderr=subprocess.DEVNULL)
    deadline = time.time() + 120
    while not os.path.exists(fifo):
        if time.time() > deadline:
            proc.kill()
            raise RuntimeError("fifo_auto never came up")
        time.sleep(0.1)
    qf = os.path.join(workdir, f"cpu-{alg}.query")
    write_query_file(qf, np.asarray(scen_queries))
    best = None
    try:
        for r in range(rounds):
            af = os.path.join(workdir, f"cpu-{alg}{r}.answer")
            os.mkfifo(af)
            with open(fifo, "w") as f:
                f.write('{"itrs": 1}\n' + f"{qf} {af} {difffile}\n")
            with open(af) as f:
                line = f.readline().strip()
            os.unlink(af)
            parts = line.split(",")
            assert int(parts[6]) == len(scen_queries), \
                f"CPU campaign unfinished: {line}"
            t_search = float(parts[9])
            best = t_search if best is None else min(best, t_search)
    finally:
        with open(fifo, "w") as f:
            f.write("__DOS_STOP__\n")
        proc.wait(timeout=30)
    return best


def _timed_cpu_build(bins, args: list, label: str) -> float:
    """Best-of-2 native CPD build (the reference baseline): the single
    shared core is subject to host contention like the device is to
    stalls, and a starved CPU baseline inflates every tpu_* speedup.
    ``--no-resume`` so rep 2 recomputes instead of skipping blocks."""
    _, best = robust_time(
        lambda: subprocess.run(
            [bins["make_cpd_auto"], *args, "--no-resume"],
            check=True, capture_output=True),
        label=label)
    return best


def _weak_scaling(side: int, chunk: int):
    """Build-time vs worker count on a virtual CPU mesh (subprocess so the
    TPU-pinned parent process cannot leak in). Same TOTAL rows each run.

    Two series per W, separating oversubscription from real overhead on
    this single-core host:

    * ``mesh``  — wall-clock of the W-shard shard_map build. The 8
      virtual devices time-slice ONE core, so this SUMS the shards'
      compute: flat-ish is the best case and says nothing about chips.
    * ``shard`` — wall-clock of ONE worker's rows built alone on one
      device (the per-chip unit of work). With the build's compiled HLO
      containing ZERO collectives (tests/test_cpd_model.py pins this), W
      real chips run exactly these programs concurrently, so the
      full-build time on W chips ≈ the max shard time — this is the
      device-compute decomposition VERDICT r03 asked for.
    """
    code = f"""
import json, os, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass   # older jax: the XLA_FLAGS form above already applies
try:    # persistent compile cache: repeat runs skip the 8 mesh compiles
    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser("~/.cache/jax_bench"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

import numpy as np, tempfile, shutil
from distributed_oracle_search_tpu.data import synth_city_graph
from distributed_oracle_search_tpu.models.cpd import (
    CPDOracle, build_worker_shard)
from distributed_oracle_search_tpu.parallel import DistributionController
from distributed_oracle_search_tpu.parallel.mesh import make_mesh
g = synth_city_graph({side}, {side}, seed=0)
mesh_s, shard_s, shard_rows = {{}}, {{}}, {{}}
for w in (1, 2, 4, 8):
    dc = DistributionController("tpu", None, w, g.n)
    mesh = make_mesh(n_workers=w)
    o = CPDOracle(g, dc, mesh=mesh)
    o.build(chunk={chunk})                      # warm-up: compile
    o = CPDOracle(g, dc, mesh=mesh)
    t0 = time.perf_counter()
    o.build(chunk={chunk})
    jax.block_until_ready(o.fm)
    mesh_s[str(w)] = round(time.perf_counter() - t0, 3)
    # per-shard series: worker 0's rows alone on ONE device
    d = tempfile.mkdtemp()
    try:
        build_worker_shard(g, dc, 0, d, chunk={chunk})  # warm-up
        shutil.rmtree(d); os.makedirs(d)
        t0 = time.perf_counter()
        build_worker_shard(g, dc, 0, d, chunk={chunk})
        shard_s[str(w)] = round(time.perf_counter() - t0, 3)
        shard_rows[str(w)] = dc.n_owned(0)
    finally:
        shutil.rmtree(d, ignore_errors=True)
print(json.dumps({{"mesh": mesh_s, "shard": shard_s,
                   "rows": shard_rows}}))
"""
    res = subprocess.run([sys.executable, "-c", code], cwd=os.path.dirname(
        os.path.abspath(__file__)), capture_output=True, text=True,
        timeout=900)
    if res.returncode != 0:
        log(f"weak-scaling subprocess failed: {res.stderr[-500:]}")
        return {}
    return json.loads(res.stdout.strip().splitlines()[-1])


def _mesh_scaling(side: int, chunk: int):
    """Multi-device mesh execution over 1/2/4/8 virtual CPU devices
    (subprocess, like :func:`_weak_scaling`): per device count, the
    lane-mesh build rate, the lane-split engine walk rate, and the
    on-mesh collective ``mat`` rate — with every answer asserted
    bit-identical to the single-device run inside the subprocess, so
    a parity break fails the section rather than recording a lie.

    The 8 virtual devices time-slice ONE core, so these rates measure
    dispatch/partition overhead, not speedup — flat-ish series = the
    mesh machinery is roughly free, which is the most a one-core host
    can prove (the speedup claim belongs to the hardware round, same
    caveat as the weak-scaling section).
    """
    code = f"""
import json, os, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
try:    # persistent compile cache: repeat runs skip the mesh compiles
    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser("~/.cache/jax_bench"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

import numpy as np, tempfile, shutil
from distributed_oracle_search_tpu.data import (
    synth_city_graph, synth_scenario)
from distributed_oracle_search_tpu.models.cpd import (
    CPDOracle, build_worker_shard)
from distributed_oracle_search_tpu.parallel import DistributionController
from distributed_oracle_search_tpu.parallel.mesh import make_mesh
from distributed_oracle_search_tpu.transport.wire import RuntimeConfig
from distributed_oracle_search_tpu.worker.engine import ShardEngine

g = synth_city_graph({side}, {side}, seed=0)
dc = DistributionController("tpu", None, 1, g.n)
queries = synth_scenario(g.n, 8192, seed=13)
rc = RuntimeConfig()
idx = tempfile.mkdtemp()
try:
    build_worker_shard(g, dc, 0, idx, chunk={chunk})
    mat_s = int(queries[0][0])
    mat_t = np.arange(g.n)[:512]
    build_s, walk_s, mat_s_sec = {{}}, {{}}, {{}}
    walk_base = mat_base = None
    for L in (1, 2, 4, 8):
        os.environ["DOS_MESH_DEVICES"] = str(L)
        # lane-mesh build (fresh ctx per L: the lane mesh is part of it)
        ctx = {{}}
        d = tempfile.mkdtemp()
        try:
            build_worker_shard(g, dc, 0, d, chunk={chunk}, ctx=ctx)
            shutil.rmtree(d); os.makedirs(d)
            t0 = time.perf_counter()
            build_worker_shard(g, dc, 0, d, chunk={chunk},
                               resume=False, ctx=ctx)
            build_s[str(L)] = round(g.n / (time.perf_counter() - t0), 1)
        finally:
            shutil.rmtree(d, ignore_errors=True)
        # lane-split walk through the engine (est-sort + buckets + unsort)
        eng = ShardEngine(g, dc, 0, idx)
        assert eng.n_lanes == L, (eng.n_lanes, L)
        eng.answer(queries, rc)
        t0 = time.perf_counter()
        c, p, f, _st = eng.answer(queries, rc)
        walk_s[str(L)] = round(len(queries) / (time.perf_counter() - t0), 1)
        if walk_base is None:
            walk_base = (c, p, f)
        else:
            for a, b in zip(walk_base, (c, p, f)):
                np.testing.assert_array_equal(a, b)
        # on-mesh collective mat: one worker shard per device
        dcl = DistributionController("tpu", None, L, g.n)
        ol = CPDOracle(g, dcl, mesh=make_mesh(n_workers=L)).build(
            chunk={chunk})
        ol.query_mat(mat_s, mat_t)
        t0 = time.perf_counter()
        for _ in range(4):
            mc, mf = ol.query_mat(mat_s, mat_t)
        mat_s_sec[str(L)] = round(
            4 * len(mat_t) / (time.perf_counter() - t0), 1)
        if mat_base is None:
            mat_base = (mc, mf)
        else:
            np.testing.assert_array_equal(mat_base[0], mc)
            np.testing.assert_array_equal(mat_base[1], mf)
finally:
    shutil.rmtree(idx, ignore_errors=True)
print(json.dumps({{"build": build_s, "walk": walk_s,
                   "mat": mat_s_sec}}))
"""
    res = subprocess.run([sys.executable, "-c", code], cwd=os.path.dirname(
        os.path.abspath(__file__)), capture_output=True, text=True,
        timeout=1200)
    if res.returncode != 0:
        log(f"mesh-scaling subprocess failed: {res.stderr[-500:]}")
        return {}
    return json.loads(res.stdout.strip().splitlines()[-1])


def _sharded_stream(xy: str, index: str, qfile: str):
    """Two CPU-backed controller processes serve one streamed campaign
    sharded: process p streams only workers ``wid % 2 == p``. Returns
    per-process wire bytes (evidence the upload work split — the real
    multi-chip win is W uplinks running concurrently, which one machine
    cannot time honestly, so the bench records the byte split instead).
    """
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    code = """
import json, os, sys
xy, index, qfile, coord, pid = (sys.argv[1], sys.argv[2], sys.argv[3],
                                sys.argv[4], int(sys.argv[5]))
from distributed_oracle_search_tpu.parallel.multihost import initialize
initialize(coordinator=coord, num_processes=2, process_id=pid,
           cpu_devices_per_process=4)
import numpy as np
from distributed_oracle_search_tpu.cli.process_query import _StreamedServe
from distributed_oracle_search_tpu.data import Graph
from distributed_oracle_search_tpu.parallel import DistributionController
g = Graph.from_xy(xy)
dc = DistributionController("mod", 4, 4, g.n)
serve = _StreamedServe(g, dc, index, chunk=64)
q = np.load(qfile)
cost, plen, fin = serve.query(q)
assert bool(np.asarray(fin).all())
print(json.dumps({"pid": pid,
                  "bytes": serve.st.last_stats["bytes_streamed"],
                  "cost_sum": int(np.asarray(cost).sum())}))
"""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["DOS_STREAM_ROW_CHUNK"] = "64"
    env["DOS_STREAM_RANGE_DENSITY"] = "0.0"
    here = os.path.dirname(os.path.abspath(__file__))
    procs = [subprocess.Popen(
        [sys.executable, "-c", code, xy, index, qfile, coord, str(pid)],
        cwd=here, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        # kill BOTH controllers: the sibling is blocked in an allgather
        # waiting for its dead peer and would orphan otherwise
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        log("sharded stream: controller subprocess timed out")
        return None
    for pid, (p, o) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            log(f"sharded stream: process {pid} rc={p.returncode}: "
                f"{o[-500:]}")
            return None
    try:
        rows = [json.loads(o.strip().splitlines()[-1]) for o in outs]
    except (json.JSONDecodeError, IndexError):
        log("sharded stream: unparseable output: "
            + " | ".join(o[-200:] for o in outs))
        return None
    if rows[0]["cost_sum"] != rows[1]["cost_sum"]:
        log(f"sharded stream: merged answers DISAGREE: {rows}")
        return None
    return [r["bytes"] for r in sorted(rows, key=lambda r: r["pid"])]


def main() -> None:
    import jax
    import numpy as np

    try:  # persistent compile cache: repeated bench runs skip XLA compiles
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/jax_bench"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # pragma: no cover - cache is best-effort
        log(f"compilation cache unavailable: {e}")

    from distributed_oracle_search_tpu.data import (
        synth_city_graph, synth_scenario, synth_diff, write_xy,
    )
    from distributed_oracle_search_tpu.models.cpd import CPDOracle
    from distributed_oracle_search_tpu.parallel import DistributionController
    from distributed_oracle_search_tpu.parallel.mesh import make_mesh
    from distributed_oracle_search_tpu.utils import Timer

    width = int(os.environ.get("BENCH_WIDTH", 96))
    height = int(os.environ.get("BENCH_HEIGHT", 96))
    n_queries = int(os.environ.get("BENCH_QUERIES", 50_000))
    chunk = int(os.environ.get("BENCH_CHUNK", 512))

    devices = jax.devices()
    log(f"devices: {devices}")
    n_workers = len(devices)

    with Timer() as t_gen:
        g = synth_city_graph(width, height, seed=0)
        queries = synth_scenario(g.n, n_queries, seed=1)
    log(f"graph n={g.n} m={g.m} K={g.max_out_degree}; "
        f"{n_queries} queries; gen {t_gen}")

    dc = DistributionController("tpu", None, n_workers, g.n)
    mesh = make_mesh(n_workers=n_workers)

    # warm-up build: compiles the relaxation program (the persistent
    # compile cache usually absorbs this, but a cache miss would smear
    # ~40s of XLA compile into the timed build)
    with Timer() as t_bwarm:
        CPDOracle(g, dc, mesh=mesh).build(chunk=chunk, store_dists=True)
    log(f"build warm-up (compile): {t_bwarm}")

    def _main_build():
        o = CPDOracle(g, dc, mesh=mesh)
        o.build(chunk=chunk, store_dists=True)
        jax.block_until_ready(o.fm)
        return o
    # band: r03/r04 records measured ~1.1-1.3 s at the default 96x96;
    # non-default sizes get no band (bands are absolute seconds).
    # drop_prev: a second live oracle (fm + dists) would double peak HBM
    oracle, t_build_s = robust_time(
        _main_build, band_s=3.0 if (width, height) == (96, 96) else None,
        label="build", drop_prev=True)
    rows_per_s = g.n / t_build_s
    log(f"CPD build: {t_build_s:.2f}s ({rows_per_s:,.0f} target rows/s, "
        f"{g.n * g.n / t_build_s / 1e9:.2f} G entries/s)")

    # ---- post-build integrity gate: persist the freshly built index and
    # run the make_cpds --verify engine over it — digest/shape-check of
    # every block against the v2 manifest. A bench run that publishes
    # numbers off a torn/rotted index is worse than a failed run.
    # BENCH_VERIFY=0 skips.
    verify_stats = {}
    if os.environ.get("BENCH_VERIFY", "1") != "0":
        from distributed_oracle_search_tpu.models.cpd import (
            verify_exit_code, verify_index,
        )

        vdir = tempfile.mkdtemp(prefix="dos-verify-")
        try:
            with Timer() as t_save:
                oracle.save(vdir)
            with Timer() as t_verify:
                vreport = verify_index(vdir, dc=dc)
            assert verify_exit_code(vreport) == 0, (
                f"post-build integrity gate failed: {vreport}")
            verify_stats = {
                "verify_seconds": round(t_verify.interval, 3),
                "verify_blocks": int(vreport["total"]),
            }
            log(f"post-build verify: {vreport['total']} block(s) clean "
                f"in {t_verify.interval:.2f}s (save {t_save.interval:.2f}s)")
        finally:
            shutil.rmtree(vdir, ignore_errors=True)

    # congestion diff for the perturbed round (reference: one round/diff)
    dsrc, ddst, dw = synth_diff(g, frac=0.1, seed=2)
    w_diff = g.weights_with_diff((dsrc, ddst, dw))

    bench_table = os.environ.get("BENCH_TABLE", "1") != "0"

    # warm-up at the full scenario shape: compiles each query program once,
    # like the reference's resident fifo_auto loading before the campaign.
    # Timed PER PROGRAM so compile regressions are attributable; the table
    # section warms itself up later — its large prepare program used to
    # run here and skewed both this number and the walk timings after it
    warmups = {}
    with Timer() as t_compile:
        with Timer() as tw:
            oracle.query(queries)
        warmups["walk"] = round(tw.interval, 2)
        with Timer() as tw:
            oracle.query(queries, w_query=w_diff)
        warmups["walk_diff"] = round(tw.interval, 2)
        with Timer() as tw:
            oracle.query_dist(queries)
        warmups["dist"] = round(tw.interval, 2)
    log(f"query warm-up (compile): {t_compile} "
        + " ".join(f"{k}={v}s" for k, v in warmups.items()))

    def best_of(fn, reps: int = 3):
        """Best-of-N timing: single-shot numbers on a tunneled device link
        jitter by 10-20%; the minimum is the reproducible figure."""
        out = None
        best = None
        for _ in range(reps):
            with Timer() as tt:
                out = fn()
            if best is None or tt.interval < best.interval:
                best = tt
        return out, best

    (cost, plen, finished), t_scen = best_of(lambda: oracle.query(queries))
    n_fin = int(finished.sum())
    qps = n_queries / t_scen.interval
    mean_plen = float(plen.mean())
    log(f"walk free-flow: {n_queries} in {t_scen} -> {qps:,.0f} q/s; "
        f"finished {n_fin}/{n_queries}, mean plen {mean_plen:.1f}")
    assert n_fin == n_queries, "benchmark correctness gate failed"

    (cost_d, plen_d, fin_d), t_diff = best_of(
        lambda: oracle.query(queries, w_query=w_diff))
    assert int(fin_d.sum()) == n_queries
    assert (cost_d >= cost).all(), "diffed costs must dominate free flow"
    log(f"walk diffed:   {n_queries} in {t_diff} -> "
        f"{n_queries / t_diff.interval:,.0f} q/s")

    (cost_g, fin_g), t_dist = best_of(lambda: oracle.query_dist(queries))
    assert (cost_g == cost).all(), "dist fast path must match the walk"
    log(f"dist gather:   {n_queries} in {t_dist} -> "
        f"{n_queries / t_dist.interval:,.0f} q/s")

    # ---- roofline: the walk does 2 scalar gathers per step per query
    # (fm slot + the packed (next-node, weight) pair); compare achieved
    # rate to a calibrated dependent-gather micro-kernel of the same
    # shape
    from distributed_oracle_search_tpu.ops.table_search import pick_buckets

    peak_gather = _calibrate_gather(g.n, n_queries)
    hbm_bw = _calibrate_hbm()
    # device-kernel time WITHOUT the host round trips: the end-to-end
    # walk pays a fixed ~90 ms device->host fetch on this tunneled link
    # plus the query pack's upload, which is transport, not kernel —
    # utilization is a kernel property, so the pack is pre-uploaded and
    # only the dispatched program is timed
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributed_oracle_search_tpu.parallel.mesh import (
        DATA_AXIS, WORKER_AXIS,
    )
    from distributed_oracle_search_tpu.parallel.sharded import _query_fn
    ra, sa, ta, va, _ = oracle.route(queries)
    qsh = NamedSharding(oracle.mesh, P(DATA_AXIS, WORKER_AXIS, None))
    ra_d, sa_d, ta_d, va_d = jax.device_put((ra, sa, ta, va), qsh)
    kern_fn = _query_fn(oracle.mesh, 0, -1)
    # stall-guarded like every timed section: r04's 0.169 s reading (vs
    # 0.113 s re-measured in a healthy window) dragged the utilization
    # figure to 0.457 — a window artifact, not a kernel property
    _, t_kern_s = robust_time(
        lambda: jax.block_until_ready(kern_fn(
            oracle.dg, oracle.fm, ra_d, sa_d, ta_d, va_d,
            oracle.dg.w_pad)),
        reps=3, band_s=0.13 if (width, height) == (96, 96) else None,
        label="walk-kernel")
    # the bucketed walk (ops.table_search n_buckets) runs each bucket to
    # its OWN max length: reconstruct issued gathers from route()'s
    # actual per-device layout (each (data, worker) plane is an
    # est-sorted, separately padded [qmax] column). Utilization compares
    # the CRITICAL-PATH device (max lanes) to the single-device peak.
    _, _, _, valid_dwq, (act, sd, sw, sq) = oracle.route(queries)
    dgrid, wgrid, qmax = valid_dwq.shape
    plen_dwq = np.zeros((dgrid, wgrid, qmax))
    plen_dwq[sd[act], sw[act], sq[act]] = np.asarray(plen)[act]
    b = pick_buckets(qmax, 0)
    qb = qmax // b
    unroll = 8
    per_bucket_max = plen_dwq.reshape(dgrid, wgrid, b, qb).max(axis=3)
    lanes_dev = (np.ceil(per_bucket_max / unroll) * unroll).sum(
        axis=2) * qb                                  # [D, W] per device
    lanes_issued = float(lanes_dev.max())
    gathers_per_step = 2          # fm slot + packed (next, weight) pair
    achieved_gather = ((n_queries / (dgrid * wgrid)) * mean_plen
                       * gathers_per_step / t_kern_s)
    issued_gather = lanes_issued * gathers_per_step / t_kern_s
    # honest lane accounting: walk_gather_utilization rewards padded
    # lanes (wider buckets inflate the issued rate while slowing the
    # answer — the table_search.py knob comment). useful_lane_fraction
    # is the unskewed figure for kernel-vs-kernel comparisons: real
    # moves of non-pad queries over ALL issued lane-steps, fleet-wide
    lanes_issued_total = float(lanes_dev.sum())
    useful_lane_fraction = (float(plen_dwq.sum()) / lanes_issued_total
                            if lanes_issued_total else 0.0)
    log(f"roofline: kernel {t_kern_s:.3f}s, peak gather "
        f"{peak_gather / 1e6:,.0f} M elem/s, "
        f"useful {achieved_gather / 1e6:,.0f} "
        f"({achieved_gather / peak_gather:.0%}), issued "
        f"{issued_gather / 1e6:,.0f} ({issued_gather / peak_gather:.0%}), "
        f"issue efficiency {achieved_gather / issued_gather:.0%}; "
        f"HBM {hbm_bw / 1e9:,.0f} GB/s")
    # XLA's own accounting of the SAME program (obs.device): FLOPs /
    # bytes-accessed / HBM footprint per compiled program, plus the
    # derived achieved-vs-peak gather-bandwidth point — the before/after
    # baseline ROADMAP item 1 (Pallas walk kernel) is judged against
    from distributed_oracle_search_tpu.obs import device as obs_device
    walk_costs = obs_device.analyze(
        kern_fn, oracle.dg, oracle.fm, ra_d, sa_d, ta_d, va_d,
        oracle.dg.w_pad)
    walk_costs = obs_device.derive_bandwidth(
        walk_costs, t_kern_s, hbm_bw / 1e9)
    if walk_costs:
        if "achieved_gbps" in walk_costs:
            log(f"roofline (XLA): {walk_costs.get('flops', 0):,.0f} "
                f"FLOPs, {walk_costs['bytes_accessed'] / 1e6:,.1f} MB "
                f"accessed -> {walk_costs['achieved_gbps']:,.1f} GB/s "
                f"achieved ({walk_costs['hbm_bw_utilization']:.0%} of "
                f"the streamed-HBM peak)")
        obs_device.record("walk-kernel", walk_costs)

    # ---- Pallas-fused walk kernel (ops.pallas_walk): the SAME routed
    # pack through the fused kernel — answers asserted bit-identical,
    # wall-clock and XLA cost capture keyed NEXT TO the XLA kernel's so
    # BENCH_DETAIL carries both sides of the roofline comparison. Real
    # chip only: interpret mode is a correctness tool, its timing says
    # nothing about the gap this kernel exists to close. BENCH_PALLAS=0
    # skips.
    pallas_roof = {}
    if (devices[0].platform == "tpu"
            and os.environ.get("BENCH_PALLAS", "1") != "0"):
        # same VMEM-fit guard as the production callers (engine /
        # CPDOracle): an over-budget shape must SKIP the section, not
        # fault on-chip and take the rest of the bench down with it
        from distributed_oracle_search_tpu.ops import pallas_walk_fits
        q_local = int(ra.shape[2]) * max(
            int(ra.shape[0]) // oracle.mesh.shape[DATA_AXIS], 1)
        fits, fit_why = pallas_walk_fits(
            oracle.dg.n, oracle.dg.k,
            int(oracle.dg.w_pad.shape[0]) - 1, q_local)
    else:
        fits, fit_why = False, ""
    if fit_why:
        log(f"walk pallas: skipped — {fit_why}")
    if fits:
        pk_fn = _query_fn(oracle.mesh, 0, -1, "pallas")

        def _pallas_walk_call():
            return jax.block_until_ready(pk_fn(
                oracle.dg, oracle.fm, ra_d, sa_d, ta_d, va_d,
                oracle.dg.w_pad))
        with Timer() as t_pwarm:
            outs_p = _pallas_walk_call()     # compile + parity capture
        cost_p, plen_p, fin_p = (np.asarray(o) for o in outs_p)
        cost_x, plen_x, fin_x = (np.asarray(o) for o in kern_fn(
            oracle.dg, oracle.fm, ra_d, sa_d, ta_d, va_d,
            oracle.dg.w_pad))
        assert (cost_p == cost_x).all() and (plen_p == plen_x).all() \
            and (fin_p == fin_x).all(), \
            "fused walk kernel diverged from the XLA walk"
        _, t_pallas_s = robust_time(_pallas_walk_call, reps=3,
                                    label="walk-kernel-pallas")
        pallas_qps = n_queries / t_pallas_s
        pallas_costs = obs_device.derive_bandwidth(
            obs_device.analyze(pk_fn, oracle.dg, oracle.fm, ra_d, sa_d,
                               ta_d, va_d, oracle.dg.w_pad),
            t_pallas_s, hbm_bw / 1e9)
        if pallas_costs:
            obs_device.record("walk-kernel-pallas", pallas_costs)
        pallas_roof = {
            "walk_pallas_kernel_seconds": round(t_pallas_s, 4),
            "walk_pallas_queries_per_sec": round(pallas_qps, 1),
            "walk_pallas_speedup": round(t_kern_s / t_pallas_s, 3),
            # the fused kernel walks the SAME bucket grid, so its lane
            # accounting is the XLA figure — keyed separately anyway so
            # a future grid change keeps the comparison honest
            "walk_pallas_useful_lane_fraction": round(
                useful_lane_fraction, 3),
            **({"walk_pallas_bytes_accessed":
                    pallas_costs.get("bytes_accessed"),
                "walk_pallas_achieved_gbps":
                    pallas_costs.get("achieved_gbps"),
                "walk_pallas_hbm_bw_utilization":
                    pallas_costs.get("hbm_bw_utilization")}
               if pallas_costs else {}),
        }
        log(f"walk pallas: kernel {t_pallas_s:.3f}s (compile "
            f"{t_pwarm.interval:.2f}s) -> {pallas_qps:,.0f} q/s, "
            f"{t_kern_s / t_pallas_s:.2f}x the XLA walk")

    # ---- measured CPU denominator: the SAME graph + scenario through the
    # native OpenMP engine (full build + resident fifo_auto campaign over
    # the real FIFO wire). This is the reference pipeline's stand-in; the
    # north-star "≥10x build" (BASELINE.md) is judged against it.
    # BENCH_CPU=0 skips.
    cpu_stats = {}
    if os.environ.get("BENCH_CPU", "1") != "0":
        bins = _native_bins()
        if bins is None:
            log("CPU baseline skipped: no native toolchain")
        else:
            cdir = tempfile.mkdtemp(prefix="dos-cpu-")
            try:
                xy = os.path.join(cdir, "city.xy")
                cidx = os.path.join(cdir, "index")
                write_xy(xy, g.xs, g.ys, g.src, g.dst, g.w)
                t_cpu_b_s = _timed_cpu_build(
                    bins, ["--input", xy, "--partmethod", "mod",
                           "--partkey", "1", "--workerid", "0",
                           "--maxworker", "1", "--outdir", cidx],
                    label="cpu-build")
                t_cpu_q = _cpu_query_campaign(bins, xy, cidx, queries,
                                              cdir)
                cores = os.cpu_count() or 1
                cpu_qps = n_queries / t_cpu_q
                build_speedup = t_cpu_b_s / t_build_s
                query_speedup = t_cpu_q / t_scen.interval
                log(f"CPU baseline ({cores} core(s)): build "
                    f"{t_cpu_b_s:.2f}s "
                    f"(tpu {build_speedup:.1f}x), campaign t_search "
                    f"{t_cpu_q:.3f}s -> {cpu_qps:,.0f} q/s "
                    f"(tpu walk {query_speedup:.2f}x, dist "
                    f"{t_cpu_q / t_dist.interval:.2f}x)")
                cpu_stats = {
                    "cpu_cores": cores,
                    # every speedup below divides by a campaign run on
                    # cpu_cores core(s). Under the reference's all-cores
                    # OpenMP deployment (README.md:95) and linear
                    # scaling, a C-core host is matched when C equals
                    # the *_parity_cores figure — the form in which the
                    # north-star "≥10x vs OpenMP all threads"
                    # (BASELINE.md) is checkable off this host.
                    "cpu_denominator": (
                        f"measured on {cores} core(s); parity_cores = "
                        "OpenMP cores (linear scaling) needed to match"),
                    "cpu_build_seconds": round(t_cpu_b_s, 2),
                    "cpu_queries_per_sec": round(cpu_qps, 1),
                    "tpu_build_speedup": round(build_speedup, 2),
                    "tpu_build_parity_cores": round(
                        build_speedup * cores, 2),
                    "tpu_query_speedup": round(query_speedup, 3),
                    "tpu_dist_speedup": round(
                        t_cpu_q / t_dist.interval, 3),
                }

                # bulk-dist round: the distance fast path is ONE gather
                # per query, so at 50k queries its time is all fixed
                # dispatch+transfer (~90 ms on this tunneled link —
                # why r03's tpu_dist_speedup sat at 1.1x). A 500k-query
                # round amortizes the fixed cost; the CPU denominator
                # is MEASURED on the same 500k (not extrapolated).
                bq = int(os.environ.get("BENCH_DIST_BULK", 500_000))
                q_bulk = synth_scenario(g.n, bq, seed=11)
                oracle.query_dist(q_bulk)        # warm-up: compile
                (cb_b, fb_b), t_bulk = best_of(
                    lambda: oracle.query_dist(q_bulk))
                assert bool(np.asarray(fb_b).all())
                t_cpu_bulk = _cpu_query_campaign(bins, xy, cidx, q_bulk,
                                                 cdir)
                log(f"dist bulk: {bq} in {t_bulk} -> "
                    f"{bq / t_bulk.interval:,.0f} q/s; CPU campaign "
                    f"{t_cpu_bulk:.3f}s (tpu dist "
                    f"{t_cpu_bulk / t_bulk.interval:.2f}x)")
                cpu_stats.update({
                    "dist_bulk_queries": bq,
                    "dist_bulk_queries_per_sec": round(
                        bq / t_bulk.interval, 1),
                    "cpu_bulk_queries_per_sec": round(bq / t_cpu_bulk, 1),
                    "tpu_dist_bulk_speedup": round(
                        t_cpu_bulk / t_bulk.interval, 3),
                })

                # native algorithm families (README: backends are
                # "interchangeable per algorithm family") — measured
                # campaign rates for astar and ch next to the batched
                # device A*'s rate, all on the same query subset (A* is
                # ~three orders slower per query than a table lookup;
                # the subset keeps the bench's runtime bounded)
                # 1024 keeps the device A*'s ~27 q/s measurement out of
                # the bench's critical path (~2.5 min at 2048)
                aq = min(int(os.environ.get("BENCH_ASTAR_QUERIES", 1024)),
                         n_queries)
                q_sub = np.asarray(queries[:aq])
                t_cpu_as = _cpu_query_campaign(bins, xy, cidx, q_sub,
                                               cdir, alg="astar")
                t_cpu_ch = _cpu_query_campaign(bins, xy, cidx, q_sub,
                                               cdir, alg="ch")
                from distributed_oracle_search_tpu.ops.batched_astar \
                    import astar_batch_np
                astar_ctx: dict = {}
                astar_batch_np(g, q_sub, ctx=astar_ctx,
                               w_key="free")     # warm-up: compile
                (ca, pa, fa, _cnt), t_dev_as = best_of(
                    lambda: astar_batch_np(g, q_sub, ctx=astar_ctx,
                                           w_key="free"), reps=2)
                assert bool(fa.all())
                assert (ca == np.asarray(cost)[:aq]).all(), \
                    "device A* must match the walk's shortest costs"
                log(f"alg families ({aq} queries): CPU astar "
                    f"{aq / t_cpu_as:,.0f} q/s, CPU ch "
                    f"{aq / t_cpu_ch:,.0f} q/s, device astar "
                    f"{aq / t_dev_as.interval:,.0f} q/s")
                cpu_stats.update({
                    "alg_family_queries": aq,
                    "cpu_astar_queries_per_sec": round(aq / t_cpu_as, 1),
                    "cpu_ch_queries_per_sec": round(aq / t_cpu_ch, 1),
                    "tpu_astar_queries_per_sec": round(
                        aq / t_dev_as.interval, 1),
                })
            finally:
                shutil.rmtree(cdir, ignore_errors=True)

    # pointer-doubling amortization path: whole-shard cost tables for the
    # DIFFED weights, then gather-speed answers. Costs O(R*N*log L)
    # gathers up front — the >1M-query trade (BASELINE.md configs[4]).
    # BENCH_TABLE=0 skips it for quick runs.
    table_stats = {}
    if bench_table:
        # warm-up: compile the prepare/lookup programs at shape on the
        # free-flow weights, so the timed run below is steady-state (and
        # the compile cost is attributable here, not smeared into it)
        with Timer() as t_tabc:
            warm = oracle.prepare_weights(None)
            # full scenario shape: a different batch size would compile a
            # different lookup program and the timed run would pay it
            oracle.query_table(warm, queries)
            jax.block_until_ready(warm[0])
            del warm
        log(f"table warm-up (compile): {t_tabc}")
        # table prepares run under the same stall guard as every build;
        # drop_prev: two live table sets would double peak device memory
        # past what the budget gate admitted
        tables, t_prep_s = robust_time(
            lambda: jax.block_until_ready(oracle.prepare_weights(w_diff)),
            drop_prev=True, label="table-prepare")
        (cost_t, plen_t, fin_t), t_tab = best_of(
            lambda: oracle.query_table(tables, queries))
        assert (cost_t == cost_d).all(), \
            "table path must match the diff walk"
        assert (plen_t == plen_d).all() and (fin_t == fin_d).all()
        # break-even from THIS run's captured rates (the pointer-doubling
        # cost model quotes this number; r03's README derived it from
        # optimistic rates — the bench is now the single source):
        # prepare pays off once saved per-query time covers it
        walk_qps_diff = n_queries / t_diff.interval
        tab_qps = n_queries / t_tab.interval
        per_q_saved = 1.0 / walk_qps_diff - 1.0 / tab_qps
        breakeven = (int(t_prep_s / per_q_saved)
                     if per_q_saved > 0 else -1)
        be_txt = (f"break-even {breakeven:,} queries" if breakeven >= 0
                  else "break-even n/a (lookups no faster than the walk)")
        log(f"diff tables:   prepare {t_prep_s:.2f}s; {n_queries} in {t_tab} -> "
            f"{tab_qps:,.0f} q/s; {be_txt}")
        table_stats = {
            "table_prepare_seconds": round(t_prep_s, 3),
            "table_queries_per_sec": round(tab_qps, 1),
            "table_breakeven_queries": breakeven,
        }
        del tables

        # fused multi-diff tables: the doubling recursion is shared
        # across diffs, so D diffs' tables cost ~one prepare's gather
        # traffic (only the packed payload widens). The sequential
        # comparison is D x this run's measured single prepare — same
        # program, same shapes, so the product is exact, not a model.
        n_tab_diffs = 4
        w4t = [w_diff] + [
            g.weights_with_diff(synth_diff(g, frac=0.1, seed=80 + i))
            for i in range(n_tab_diffs - 1)]
        with Timer() as t_tm_c:          # compile (fresh program)
            warm4 = oracle.prepare_weights_multi(w4t)
            oracle.query_table_multi(warm4, queries)
            jax.block_until_ready(warm4[0])
            del warm4
        log(f"multi-table warm-up (compile): {t_tm_c}")
        tables4, t_prep4_s = robust_time(
            lambda: jax.block_until_ready(
                oracle.prepare_weights_multi(w4t)),
            drop_prev=True, label="table-prepare-multi")
        (cm4t, pm4t, fm4t), t_tab4 = best_of(
            lambda: oracle.query_table_multi(tables4, queries))
        assert (cm4t[0] == cost_t).all(), \
            "fused table plane 0 must match the single-diff tables"
        amort = n_tab_diffs * t_prep_s / t_prep4_s
        log(f"fused tables: {n_tab_diffs} diffs prepared in "
            f"{t_prep4_s:.2f}s "
            f"(vs {n_tab_diffs} x {t_prep_s:.1f}s sequential = "
            f"{amort:.2f}x amortization); lookups "
            f"{n_queries / t_tab4.interval:,.0f} q/s x {n_tab_diffs} "
            f"diffs/gather")
        table_stats.update({
            "table_multi_diffs": n_tab_diffs,
            "table_multi_prepare_seconds": round(t_prep4_s, 3),
            "table_multi_amortization": round(amort, 3),
            "table_multi_queries_per_sec": round(
                n_queries / t_tab4.interval, 1),
        })
        del tables4

    # ---- scale section: 102k-node city, single chip. One complete worker
    # shard (div/8) built with the fast-sweeping kernel and served
    # STREAMED from the on-disk block files — the serving mode for indexes
    # that exceed HBM (full fm at this scale: N^2 = 10.5 GB single-shard).
    scale_stats = {}
    if os.environ.get("BENCH_SCALE", "1") != "0":
        from distributed_oracle_search_tpu.models.cpd import (
            build_worker_shard, write_index_manifest,
        )
        from distributed_oracle_search_tpu.models.streamed import (
            StreamedCPDOracle,
        )

        side = int(os.environ.get("BENCH_SCALE_SIDE", 320))
        sq = int(os.environ.get("BENCH_SCALE_QUERIES", 20_000))
        g2 = synth_city_graph(side, side, seed=0)
        w_scale = 8
        per_w = -(-g2.n // w_scale)
        dc2 = DistributionController("div", per_w, w_scale, g2.n)
        outdir = tempfile.mkdtemp(prefix="dos-scale-")
        try:
            log(f"scale: n={g2.n} building worker 0 shard "
                f"({dc2.n_owned(0)} rows, sweep kernel)...")
            # warm-up: compile the sweep program at the build chunk shape
            # (persistent-cached across runs) so the timed build is
            # steady-state like every other section
            from distributed_oracle_search_tpu.models.cpd import (
                pick_build_kernel,
            )
            from distributed_oracle_search_tpu.ops import DeviceGraph
            from distributed_oracle_search_tpu.ops.grid_sweep import (
                build_fm_columns_sweep,
            )
            _, gg2 = pick_build_kernel(g2, "sweep")
            dg2 = DeviceGraph.from_graph(g2)
            sc_chunk = int(os.environ.get("BENCH_SCALE_CHUNK", 1024))
            jax.block_until_ready(build_fm_columns_sweep(
                dg2, gg2, np.arange(sc_chunk, dtype=np.int32)))
            # chunk=1024: the sweep kernel's while-body holds several
            # skewed [CA, H, B] buffers; 1024 rows (~5 GB working set at
            # this graph size) measured 20% faster per row than 512 and
            # fits a 16 GB chip with the pipelined double-block drain

            def _reset_scale():         # builds resume off block files
                # the ledger goes too: it grows a journal line per
                # block per rep, and leaving it would hand the next
                # rep a fatter journal even with resume off
                for f in os.listdir(outdir):
                    if f.startswith(("cpd-", "build-")):
                        os.unlink(os.path.join(outdir, f))
            # band: candidate r04 measured 43 s (297 rows/s); the record
            # capture's 116 s was a documented >2.5x stall — 70 s flags
            # it. Absolute-seconds bands only apply at the default knobs.
            scale_default = side == 320 and sc_chunk == 1024
            # resume=False hoists the per-block ledger re-read out of
            # the timed region: scale_build_rows_per_sec measures
            # compute + block writes, not journal parsing (the reset
            # already guarantees every block is missing)
            _, t_b2_s = robust_time(
                lambda: build_worker_shard(g2, dc2, 0, outdir,
                                           chunk=sc_chunk, method="sweep",
                                           resume=False),
                reset=_reset_scale,
                band_s=70.0 if scale_default else None,
                label="scale-build")
            rows0 = dc2.n_owned(0)
            rps2 = rows0 / t_b2_s
            full_est = g2.n / rps2
            write_index_manifest(outdir, dc2, workers=[0])
            log(f"scale build: {rows0} rows in {t_b2_s:.2f}s -> "
                f"{rps2:,.0f} rows/s ({rps2 * g2.n / 1e9:.2f} G "
                f"entries/s), full-index extrapolation {full_est:,.0f}s")

            rng = np.random.default_rng(3)
            q2 = np.stack([rng.integers(0, g2.n, sq),
                           rng.integers(0, rows0, sq)], axis=1)
            # explicit cache budget: the tunneled backend reports no
            # memory_stats, and the conservative 1 GB fallback would
            # evict inside this section's 1.7 GB chunk working set
            st = StreamedCPDOracle(g2, dc2, outdir, row_chunk=4096,
                                   cache_bytes=4 << 30)
            st.query(q2[:256])                 # warm-up: compile
            # prime the persisted RLE sidecars UNTIMED (the first-ever
            # round pays the one-time encode, like the compile warm-up
            # pays XLA): every timed rep below then runs the same
            # deployment-steady-state cold path — device caches empty,
            # compressed index on disk — so best-of reps are symmetric
            st.clear_cache()
            st.query(q2)
            # cold round: every rep drops the LRU first so each pays
            # the full (compressed) upload; wire bytes are
            # deterministic across reps, so the stats read after the
            # loop describe the best run too. Band: ~3 s measured for
            # the sidecar-backed path; 15 s flags a stall

            def _cold():
                st.clear_cache()
                return st.query(q2)
            (c2, p2, f2), t_q2_s = robust_time(
                _cold,
                band_s=15.0 if scale_default and sq == 20_000 else None,
                label="scale-cold-stream")
            assert bool(f2.all()), "scale campaign left unfinished queries"
            cold_qps = sq / t_q2_s
            # snapshot BEFORE the warm rounds below overwrite last_stats
            # with zero-upload rounds (the road section does the same)
            scale_cold_stats = dict(st.last_stats)
            cold_mb = st.last_stats["bytes_streamed"] / 1e6
            # captured HERE: the warm best_of rounds below overwrite
            # last_stats with zero-byte rounds
            cold_raw_mb = st.last_stats["bytes_raw"] / 1e6
            # packing that RAN, not merely the enabled flag (chunks
            # fall back individually when too many entries escape)
            cold_pack4 = st.last_stats["chunks_packed"] > 0
            mbps = st.last_stats["bytes_streamed"] / t_q2_s / 1e6
            log(f"scale streamed (cold): {sq} queries in {t_q2_s:.2f}s -> "
                f"{cold_qps:,.0f} q/s; streamed {cold_mb:,.0f} MB wire"
                f" ({cold_raw_mb:,.0f} MB raw fm"
                f"{', 4-bit packed' if cold_pack4 else ''};"
                f" {mbps:,.0f} MB/s incl. walk)")
            # round 2+ — the serving steady state (a resident streaming
            # server answers MANY rounds over overlapping targets, one
            # per diff, reference process_query.py:178): the device LRU
            # holds every chunk, so no bytes move
            (c2w, p2w, f2w), t_q2w = best_of(lambda: st.query(q2))
            assert st.last_stats["bytes_streamed"] == 0, \
                "warm round must be fully cache-resident"
            assert (c2w == c2).all() and (p2w == p2).all()
            warm_qps = sq / t_q2w.interval
            log(f"scale streamed (warm, chunks cached): {sq} in {t_q2w} "
                f"-> {warm_qps:,.0f} q/s; 0 MB streamed")
            scale_stats = {
                "scale_nodes": g2.n,
                "scale_build_rows": rows0,
                "scale_build_seconds": round(t_b2_s, 2),
                "scale_build_rows_per_sec": round(rps2, 1),
                "scale_full_build_est_seconds": round(full_est, 1),
                # cold keeps the r03 key (rounds stay comparable across
                # bench artifacts); the cache-warm steady state is its
                # own key, never a silent redefinition. scale_stream_mb
                # stays the RAW fm bytes the cold round served (the r03
                # unit); the wire bytes and packing state get their own
                # keys so the 4-bit-packed uplink is visible, not a
                # silent 2x accounting change
                "scale_stream_queries_per_sec": round(cold_qps, 1),
                "scale_stream_mb": round(cold_raw_mb, 1),
                "scale_stream_wire_mb": round(cold_mb, 1),
                "scale_stream_pack4": cold_pack4,
                # which wire path the cold round of record actually ran
                # (RLE chunks / persisted-sidecar hits out of row_chunks)
                "scale_stream_rle_chunks":
                    scale_cold_stats["chunks_rle"],
                "scale_stream_sidecar_hits":
                    scale_cold_stats["sidecar_hits"],
                "scale_stream_warm_queries_per_sec": round(warm_qps, 1),
                "scale_stream_warm_mb": 0.0,
            }

            # resident serving of the SAME shard: 1.3 GB int8 fits HBM —
            # this is one chip of the real multi-chip deployment (each
            # chip holds its worker's shard resident; streaming is for
            # the regime where even one shard exceeds HBM)
            import jax.numpy as jnp

            from distributed_oracle_search_tpu.ops.table_search import (
                table_search_batch,
            )

            blocks = sorted(f for f in os.listdir(outdir)
                            if f.startswith("cpd-w00000"))
            fm0 = jnp.asarray(np.concatenate(
                [np.load(os.path.join(outdir, f)) for f in blocks]))
            # div partition: worker 0's owned row index == target node id
            est2 = (np.abs(g2.xs[q2[:, 0]] - g2.xs[q2[:, 1]])
                    + np.abs(g2.ys[q2[:, 0]] - g2.ys[q2[:, 1]]))
            order2 = np.argsort(est2, kind="stable")
            qpad = 1 << (sq - 1).bit_length()
            rr = np.zeros(qpad, np.int32)
            ss = np.zeros(qpad, np.int32)
            tt2 = np.zeros(qpad, np.int32)
            vv = np.zeros(qpad, bool)
            rr[:sq] = q2[order2, 1]
            ss[:sq] = q2[order2, 0]
            tt2[:sq] = q2[order2, 1]
            vv[:sq] = True

            def resident():
                return jax.block_until_ready(table_search_batch(
                    dg2, fm0, rr, ss, tt2, dg2.w_pad, valid=vv))
            (cr, pr, fr), t_res = best_of(resident)
            assert bool(np.asarray(fr)[:sq].all())
            assert (np.asarray(cr)[np.argsort(order2)] == c2).all(), \
                "resident shard serve must match streamed answers"
            rqps = sq / t_res.interval
            log(f"scale resident: {sq} queries in {t_res} -> "
                f"{rqps:,.0f} q/s (worker-0 shard, "
                f"{fm0.nbytes / 1e9:.1f} GB on HBM)")
            scale_stats["scale_resident_queries_per_sec"] = round(rqps, 1)
            del fm0

            # CPU at the same scale (BENCH_CPU=0 skips): build rate from
            # a 512-row sub-worker (div/512 — a full worker shard would
            # take minutes), serve from the SAME on-disk index the sweep
            # kernel just wrote (block files are builder-agnostic,
            # tests/test_native.py block parity)
            if os.environ.get("BENCH_CPU", "1") != "0":
                bins = _native_bins()
                if bins is not None:
                    xy2 = os.path.join(outdir, "scale.xy")
                    write_xy(xy2, g2.xs, g2.ys, g2.src, g2.dst, g2.w)
                    sub_rows = 512
                    t_cb2_s = _timed_cpu_build(
                        bins, ["--input", xy2, "--partmethod", "div",
                               "--partkey", str(sub_rows),
                               "--workerid", "0", "--maxworker",
                               str(-(-g2.n // sub_rows)), "--outdir",
                               os.path.join(outdir, "cpuidx")],
                        label="scale-cpu-build")
                    cpu_rps2 = sub_rows / t_cb2_s
                    t_cpu_q2 = _cpu_query_campaign(
                        bins, xy2, outdir, q2, outdir,
                        partmethod="div", partkey=per_w, workerid=0,
                        maxworker=w_scale)
                    cpu_qps2 = sq / t_cpu_q2
                    log(f"scale CPU: build {cpu_rps2:,.0f} rows/s "
                        f"(tpu {rps2 / cpu_rps2:.1f}x), campaign "
                        f"t_search {t_cpu_q2:.3f}s -> {cpu_qps2:,.0f} "
                        f"q/s (tpu streamed {t_cpu_q2 / t_q2_s:.2f}"
                        f"x)")
                    cores = os.cpu_count() or 1
                    scale_stats.update({
                        "scale_cpu_build_rows_per_sec": round(cpu_rps2, 1),
                        "scale_cpu_queries_per_sec": round(cpu_qps2, 1),
                        "scale_tpu_build_speedup": round(
                            rps2 / cpu_rps2, 2),
                        "scale_build_parity_cores": round(
                            rps2 / cpu_rps2 * cores, 2),
                        "scale_tpu_stream_speedup": round(
                            t_cpu_q2 / t_q2_s, 3),
                        "scale_tpu_stream_warm_speedup": round(
                            t_cpu_q2 / t_q2w.interval, 3),
                        "scale_tpu_resident_speedup": round(
                            t_cpu_q2 / t_res.interval, 3),
                    })
        finally:
            shutil.rmtree(outdir, ignore_errors=True)

    # ---- road section: non-grid, degree-skewed 264k-node network (the
    # DIMACS stand-in, BASELINE.md configs[5]) — the regime where the
    # grid/shift build gates MUST fall back gracefully. Build via the ELL
    # fallback on TPU vs per-source Dijkstra on CPU; serve streamed and
    # resident from the same index. BENCH_ROAD=0 skips.
    road_stats = {}
    comp_stats = {}
    if os.environ.get("BENCH_ROAD", "1") != "0":
        import jax.numpy as jnp

        from distributed_oracle_search_tpu.data import synth_road_network
        from distributed_oracle_search_tpu.models.cpd import (
            pick_build_kernel, write_index_manifest,
        )
        from distributed_oracle_search_tpu.models.streamed import (
            StreamedCPDOracle,
        )
        from distributed_oracle_search_tpu.ops import DeviceGraph
        from distributed_oracle_search_tpu.ops.shift_relax import (
            split_coverage,
        )
        from distributed_oracle_search_tpu.ops.table_search import (
            table_search_batch,
        )

        rn = int(os.environ.get("BENCH_ROAD_NODES", 264_000))
        g3 = synth_road_network(rn, seed=0)
        _, ws_raw, _, wl_raw = g3.shift_split()
        cov_raw = split_coverage(ws_raw, wl_raw)
        with Timer() as t_rcm:
            g3 = g3.reorder(g3.rcm_order())
        _, ws_rcm, _, wl_rcm = g3.shift_split()
        cov_rcm = split_coverage(ws_rcm, wl_rcm)
        kind3, st3k = pick_build_kernel(g3, "auto")
        log(f"road: n={g3.n} m={g3.m} K={g3.max_out_degree}; rcm reorder "
            f"{t_rcm}; shift coverage {cov_raw:.1%} -> {cov_rcm:.1%}; "
            f"auto build kernel = {kind3} (grid/shift gates fell back "
            f"as designed)")

        sub = 512                       # rows per serving sub-worker
        mw3 = -(-g3.n // sub)
        dc3 = DistributionController("div", sub, mw3, g3.n)
        out3 = tempfile.mkdtemp(prefix="dos-road-")
        try:
            # TPU build via the auto-picked kernel (delta-stepping
            # frontier queue on the RCM-ordered road graph). 2048 timed
            # rows: the frontier's per-iteration cost amortizes over
            # the batch (measured ~10% more rows/s than 512-row calls)
            # and the fixed fetch/dispatch costs quarter; rows/s stays
            # directly comparable to the 512-row CPU build below (both
            # are per-row rates of linear-in-rows work)
            trows = int(os.environ.get("BENCH_ROAD_ROWS", 2048))
            dg3 = DeviceGraph.from_graph(g3)
            if kind3 == "frontier":
                from distributed_oracle_search_tpu.ops.frontier_relax \
                    import build_fm_columns_frontier
                build3 = lambda t: build_fm_columns_frontier(  # noqa: E731
                    dg3, st3k, t)
            elif kind3 == "ellsplit":
                from distributed_oracle_search_tpu.ops.ell_split import (
                    build_fm_columns_ellsplit,
                )
                build3 = lambda t: build_fm_columns_ellsplit(  # noqa: E731
                    dg3, st3k, t)
            elif kind3 == "shift":
                from distributed_oracle_search_tpu.ops.shift_relax import (
                    build_fm_columns_shift,
                )
                build3 = lambda t: build_fm_columns_shift(  # noqa: E731
                    dg3, st3k, t)
            elif kind3 == "sweep":
                from distributed_oracle_search_tpu.ops.grid_sweep import (
                    build_fm_columns_sweep,
                )
                build3 = lambda t: build_fm_columns_sweep(  # noqa: E731
                    dg3, st3k, t)
            else:
                from distributed_oracle_search_tpu.ops import (
                    build_fm_columns,
                )
                build3 = lambda t: build_fm_columns(  # noqa: E731
                    dg3, jnp.asarray(t))
            from distributed_oracle_search_tpu.models.cpd import fetch_fm
            tgt64 = np.arange(trows, dtype=np.int32)
            fetch_fm(build3(tgt64))           # compile build + encode
            # end-to-end incl. the host materialization (the build's
            # real product is block files): the RLE fetch ships ~3
            # bytes/run instead of the raw bytes, which a 12-60 MB/s
            # link window turned into up to half the build time.
            # Band: ~27 s for 2048 rows at the default 264k nodes,
            # scaled linearly for other BENCH_ROAD_ROWS settings
            fm64, t_b3_s = robust_time(
                lambda: fetch_fm(build3(tgt64)),             # [trows, N]
                band_s=(40.0 * trows / 2048 if rn == 264_000
                        else None),
                label="road-build")
            tpu_rps3 = trows / t_b3_s
            log(f"road TPU build ({kind3}): {trows} rows in "
                f"{t_b3_s:.2f}s -> {tpu_rps3:,.1f} rows/s")

            # ---- compressed residency (ROADMAP item 1): the SAME road
            # rows resident raw vs RLE/pack4-compressed with
            # decompress-at-use (models.resident, DOS_CPD_RESIDENT).
            # The ratio is a codec property of THIS shard's bytes; the
            # walk figures time the serving path's actual shape — the
            # batch's distinct target rows inflate on device, then the
            # same walk kernel runs — against the raw-resident walk on
            # identical queries. BENCH_COMPRESSED=0 skips.
            if os.environ.get("BENCH_COMPRESSED", "1") != "0":
                from distributed_oracle_search_tpu.models.resident \
                    import make_resident

                ctab, ccodec = make_resident(fm64, codec="auto")
                if ccodec == "raw":
                    log("compressed: auto codec degraded to raw "
                        "(incompressible shard); section skipped")
                else:
                    cratio = fm64.nbytes / ctab.nbytes
                    log(f"compressed: {ccodec} residency "
                        f"{fm64.nbytes / 2**20:.1f} MB -> "
                        f"{ctab.nbytes / 2**20:.1f} MB "
                        f"({cratio:.2f}x)")
                    rngc = np.random.default_rng(9)
                    cq = int(os.environ.get("BENCH_COMPRESSED_QUERIES",
                                            20_000))
                    qsc = rngc.integers(0, g3.n, cq)
                    qtc = rngc.integers(0, trows, cq)
                    estc = (np.abs(g3.xs[qsc] - g3.xs[qtc])
                            + np.abs(g3.ys[qsc] - g3.ys[qtc]))
                    oc = np.argsort(estc, kind="stable")
                    qpc = 1 << (cq - 1).bit_length()
                    rrc = np.zeros(qpc, np.int32)
                    ssc = np.zeros(qpc, np.int32)
                    ttc = np.zeros(qpc, np.int32)
                    vvc = np.zeros(qpc, bool)
                    rrc[:cq] = qtc[oc]
                    ssc[:cq] = qsc[oc]
                    ttc[:cq] = qtc[oc]
                    vvc[:cq] = True
                    fmcr = jnp.asarray(fm64)
                    (ccr, _pcr, _fcr), t_craw = best_of(
                        lambda: jax.block_until_ready(table_search_batch(
                            dg3, fmcr, rrc, ssc, ttc, dg3.w_pad,
                            valid=vvc)))
                    # the engine's decompress-at-use shape: distinct
                    # rows inflate once, row ids remap onto the dense
                    # block, the walk is unchanged
                    urc, rinvc = np.unique(rrc, return_inverse=True)
                    rpadc = 1 << (len(urc) - 1).bit_length()
                    ruc = np.zeros(rpadc, np.int32)
                    ruc[:len(urc)] = urc
                    ruc_d = jnp.asarray(ruc)
                    rrc2 = rinvc.reshape(-1).astype(np.int32)

                    def comp_walk():
                        fmw = ctab.decompress_rows(ruc_d)
                        return jax.block_until_ready(table_search_batch(
                            dg3, fmw, rrc2, ssc, ttc, dg3.w_pad,
                            valid=vvc))

                    (ccc, _pcc, _fcc), t_ccmp = best_of(comp_walk)
                    assert (np.asarray(ccc) == np.asarray(ccr)).all(), \
                        "compressed-resident walk != raw-resident walk"
                    _, t_cdec = best_of(
                        lambda: jax.block_until_ready(
                            ctab.decompress_rows(ruc_d)))
                    cqps_raw = cq / t_craw.interval
                    cqps_cmp = cq / t_ccmp.interval
                    log(f"compressed walk: raw {cqps_raw:,.0f} q/s vs "
                        f"{ccodec} {cqps_cmp:,.0f} q/s "
                        f"({cqps_cmp / cqps_raw:.2f}x; decompress "
                        f"{t_cdec.interval * 1e3:.1f} ms/batch for "
                        f"{len(urc)} distinct rows)")
                    comp_stats = {
                        "compressed_codec": ccodec,
                        "compressed_rows": trows,
                        "compressed_raw_mb": round(
                            fm64.nbytes / 2**20, 1),
                        "compressed_resident_mb": round(
                            ctab.nbytes / 2**20, 1),
                        "cpd_resident_bytes_ratio": round(cratio, 2),
                        "compressed_raw_walk_queries_per_sec": round(
                            cqps_raw, 1),
                        "compressed_walk_queries_per_sec": round(
                            cqps_cmp, 1),
                        "compressed_vs_raw_walk_ratio": round(
                            cqps_cmp / cqps_raw, 3),
                        "compressed_decompress_seconds": round(
                            t_cdec.interval, 4),
                    }
                    del fmcr, ctab

            bins = (_native_bins()
                    if os.environ.get("BENCH_CPU", "1") != "0" else None)
            if bins is not None:
                xy3 = os.path.join(out3, "road.xy")
                write_xy(xy3, g3.xs, g3.ys, g3.src, g3.dst, g3.w)
                t_cb3_s = _timed_cpu_build(
                    bins, ["--input", xy3, "--partmethod", "div",
                           "--partkey", str(sub), "--workerid", "0",
                           "--maxworker", str(mw3), "--outdir", out3],
                    label="road-cpu-build")
                cpu_rps3 = sub / t_cb3_s
                # correctness gate: ELL build and native Dijkstra must
                # produce bit-identical first moves on this graph too
                blk0 = np.load(os.path.join(
                    out3, "cpd-w00000-b00000.npy"))
                # the native sub-worker owns 512 rows; parity on the
                # overlap (the kernels' tie-breaks must agree row-wise)
                npar = min(trows, len(blk0))
                assert (blk0[:npar] == fm64[:npar]).all(), \
                    "road: TPU ELL fm rows != native Dijkstra rows"
                log(f"road CPU build: {sub} rows in {t_cb3_s:.2f}s -> "
                    f"{cpu_rps3:,.1f} rows/s (tpu "
                    f"{tpu_rps3 / cpu_rps3:.2f}x); fm parity ok")

                write_index_manifest(out3, dc3, workers=[0])
                rng = np.random.default_rng(5)
                rq = int(os.environ.get("BENCH_ROAD_QUERIES", 20_000))
                q3 = np.stack([rng.integers(0, g3.n, rq),
                               rng.integers(0, sub, rq)], axis=1)
                st3 = StreamedCPDOracle(g3, dc3, out3, row_chunk=512,
                                        cache_bytes=4 << 30)
                st3.query(q3[:256])
                st3.clear_cache()
                st3.query(q3)     # prime RLE sidecars untimed (encode
                # is one-time; timed reps below all run the same
                # compressed-index cold path — see the scale section)

                def _cold3():             # cold round pays every upload
                    st3.clear_cache()
                    return st3.query(q3)
                (c3, p3, f3), t_q3_s = robust_time(
                    _cold3,
                    band_s=(8.0 if rn == 264_000 and rq == 20_000
                            else None),
                    label="road-cold-stream")
                assert bool(f3.all())
                road_cold_stats = dict(st3.last_stats)
                (c3w, p3w, f3w), t_q3w = best_of(lambda: st3.query(q3))
                assert st3.last_stats["bytes_streamed"] == 0
                assert (c3w == c3).all()
                log(f"road streamed: cold {rq} in {t_q3_s:.2f}s -> "
                    f"{rq / t_q3_s:,.0f} q/s; warm {t_q3w} -> "
                    f"{rq / t_q3w.interval:,.0f} q/s (chunks cached)")

                # resident worker-0 shard (135 MB) — the per-chip unit
                fm0r = jnp.asarray(blk0)
                est3 = (np.abs(g3.xs[q3[:, 0]] - g3.xs[q3[:, 1]])
                        + np.abs(g3.ys[q3[:, 0]] - g3.ys[q3[:, 1]]))
                o3 = np.argsort(est3, kind="stable")
                qp3 = 1 << (rq - 1).bit_length()
                rr3 = np.zeros(qp3, np.int32)
                ss3 = np.zeros(qp3, np.int32)
                tt3 = np.zeros(qp3, np.int32)
                vv3 = np.zeros(qp3, bool)
                rr3[:rq] = q3[o3, 1]
                ss3[:rq] = q3[o3, 0]
                tt3[:rq] = q3[o3, 1]
                vv3[:rq] = True
                (cr3, pr3, fr3), t_r3 = best_of(
                    lambda: jax.block_until_ready(table_search_batch(
                        dg3, fm0r, rr3, ss3, tt3, dg3.w_pad, valid=vv3)))
                assert bool(np.asarray(fr3)[:rq].all())
                assert (np.asarray(cr3)[np.argsort(o3)] == c3).all()
                rqps3 = rq / t_r3.interval
                t_cq3 = _cpu_query_campaign(
                    bins, xy3, out3, q3, out3, partmethod="div",
                    partkey=sub, workerid=0, maxworker=mw3)
                log(f"road resident: {rq} in {t_r3} -> {rqps3:,.0f} q/s; "
                    f"CPU campaign {t_cq3:.3f}s -> "
                    f"{rq / t_cq3:,.0f} q/s (tpu resident "
                    f"{t_cq3 / t_r3.interval:.2f}x)")

                # congestion round at road scale — the reference campaign
                # shape is one round per diff (process_query.py:178);
                # r03 only ever served roads free-flow. Same queries,
                # perturbed weights, all three servers.
                from distributed_oracle_search_tpu.data import (
                    synth_diff, write_diff,
                )
                dsrc3, ddst3, dw3 = synth_diff(g3, frac=0.1, seed=7)
                w_diff3 = g3.weights_with_diff((dsrc3, ddst3, dw3))
                diff3 = os.path.join(out3, "road.xy.diff")
                write_diff(diff3, dsrc3, ddst3, dw3)
                # streamed diff round: chunks already cached; best_of
                # like every other serve figure (single-shot timings
                # carry the ±20% link jitter). The per-call diff-weight
                # upload stays inside the timer — it IS part of serving
                # a diff round.
                (cd3, pd3, fd3), t_qd3 = best_of(
                    lambda: st3.query(q3, w_query=w_diff3))
                assert bool(fd3.all())
                assert st3.last_stats["bytes_streamed"] == 0, \
                    "diff round must reuse the free-flow round's chunks"
                assert (cd3 >= c3).all(), \
                    "road diffed costs must dominate free flow"
                w_pad3d = jnp.asarray(g3.padded_weights(w_diff3),
                                      jnp.int32)
                (crd3, prd3, frd3), t_rd3 = best_of(
                    lambda: jax.block_until_ready(table_search_batch(
                        dg3, fm0r, rr3, ss3, tt3, w_pad3d, valid=vv3)))
                assert (np.asarray(crd3)[np.argsort(o3)] == cd3).all(), \
                    "road diff: resident and streamed answers differ"
                t_cqd3 = _cpu_query_campaign(
                    bins, xy3, out3, q3, out3, partmethod="div",
                    partkey=sub, workerid=0, maxworker=mw3,
                    difffile=diff3)
                log(f"road diff round: streamed {rq} in {t_qd3} -> "
                    f"{rq / t_qd3.interval:,.0f} q/s; resident {t_rd3} "
                    f"-> {rq / t_rd3.interval:,.0f} q/s; CPU campaign "
                    f"{t_cqd3:.3f}s -> {rq / t_cqd3:,.0f} q/s (tpu "
                    f"resident {t_cqd3 / t_rd3.interval:.2f}x)")

                # fused multi-diff: D congestion rounds in ONE walk
                # (trajectories are diff-independent — the reference
                # must run D sequential rounds, process_query.py:178).
                # All weight rows are pre-uploaded for BOTH paths so
                # the comparison times walks, not today's uplink.
                from distributed_oracle_search_tpu.ops.table_search \
                    import table_search_multi
                n_rounds = 4
                w4 = [g3.weights_with_diff(synth_diff(
                          g3, frac=0.1, seed=70 + i))
                      for i in range(n_rounds)]
                w4_seq = [jnp.asarray(g3.padded_weights(w), jnp.int32)
                          for w in w4]
                w4_pads = jnp.asarray(
                    np.stack([g3.padded_weights(w) for w in w4]),
                    jnp.int32)

                def seq_rounds():
                    return [jax.block_until_ready(table_search_batch(
                        dg3, fm0r, rr3, ss3, tt3, wd, valid=vv3))
                        for wd in w4_seq]

                def fused_rounds():
                    return jax.block_until_ready(table_search_multi(
                        dg3, fm0r, rr3, ss3, tt3, w4_pads, valid=vv3))

                seq_out, t_seq4 = best_of(seq_rounds)
                (cm4, pm4, fm4), t_fus4 = best_of(fused_rounds)
                for di, (cs, ps, fs) in enumerate(seq_out):
                    assert (np.asarray(cm4[di]) == np.asarray(cs)).all(), \
                        f"fused round {di} != sequential round"
                log(f"road multi-diff: {n_rounds} rounds fused in "
                    f"{t_fus4} vs sequential {t_seq4} "
                    f"({t_seq4.interval / t_fus4.interval:.2f}x; "
                    f"{n_rounds * rq / t_fus4.interval:,.0f} "
                    f"answers/s fused)")

                cores = os.cpu_count() or 1
                road_stats = {
                    "road_nodes": g3.n,
                    "road_edges": g3.m,
                    "road_shift_coverage_raw": round(cov_raw, 4),
                    "road_shift_coverage_rcm": round(cov_rcm, 4),
                    "road_build_kernel": kind3,
                    "road_build_rows": trows,
                    "road_tpu_build_rows_per_sec": round(tpu_rps3, 2),
                    "road_cpu_build_rows_per_sec": round(cpu_rps3, 2),
                    "road_build_parity_cores": round(
                        tpu_rps3 / cpu_rps3 * cores, 2),
                    "road_stream_queries_per_sec": round(
                        rq / t_q3_s, 1),
                    "road_stream_rle_chunks":
                        road_cold_stats["chunks_rle"],
                    "road_stream_sidecar_hits":
                        road_cold_stats["sidecar_hits"],
                    "road_stream_wire_mb": round(
                        road_cold_stats["bytes_streamed"] / 1e6, 1),
                    "road_stream_warm_queries_per_sec": round(
                        rq / t_q3w.interval, 1),
                    "road_resident_queries_per_sec": round(rqps3, 1),
                    "road_cpu_queries_per_sec": round(rq / t_cq3, 1),
                    "road_tpu_resident_speedup": round(
                        t_cq3 / t_r3.interval, 3),
                    "road_diff_stream_queries_per_sec": round(
                        rq / t_qd3.interval, 1),
                    "road_diff_resident_queries_per_sec": round(
                        rq / t_rd3.interval, 1),
                    "road_diff_cpu_queries_per_sec": round(
                        rq / t_cqd3, 1),
                    "road_diff_tpu_resident_speedup": round(
                        t_cqd3 / t_rd3.interval, 3),
                    "road_multidiff_rounds": n_rounds,
                    "road_multidiff_fused_seconds": round(
                        t_fus4.interval, 3),
                    "road_multidiff_sequential_seconds": round(
                        t_seq4.interval, 3),
                    "road_multidiff_fused_speedup": round(
                        t_seq4.interval / t_fus4.interval, 3),
                }
        finally:
            shutil.rmtree(out3, ignore_errors=True)

    # ---- delta builds: incremental CPD refresh for one diff epoch vs a
    # full rebuild on the retimed graph (ROADMAP item 1's second half).
    # Deliberately CPU-measurable: the ratio is work-skipped / work-done
    # — a property of the tense-edge dirty pass and the block byte-copy
    # path, not of the device. The delta timing INCLUDES the dirty-set
    # pass and the manifest write (that is the end-to-end refresh a
    # traffic epoch pays). BENCH_DELTA=0 skips.
    delta_stats = {}
    if os.environ.get("BENCH_DELTA", "1") != "0":
        from distributed_oracle_search_tpu.data import write_diff
        from distributed_oracle_search_tpu.data.graph import (
            Graph as _DGraph,
        )
        from distributed_oracle_search_tpu.models.cpd import (
            build_worker_shard, delta_build_index, epoch_index_dir,
            write_index_manifest,
        )

        dside = int(os.environ.get("BENCH_DELTA_SIDE", 48))
        dhot = int(os.environ.get("BENCH_DELTA_HOT", 2))
        gd = synth_city_graph(dside, dside, seed=2)
        wd = 4
        per_wd = -(-gd.n // wd)
        dcd = DistributionController("div", per_wd, wd, gd.n)
        ddir = tempfile.mkdtemp(prefix="dos-delta-")
        try:
            log(f"delta build: n={gd.n}, {wd} shards, {dhot}-edge "
                "congestion hotspot...")
            for wid in range(wd):
                build_worker_shard(gd, dcd, wid, ddir, chunk=512)
            write_index_manifest(ddir, dcd)
            # LOCALIZED retime — a congestion hotspot (edges from one
            # small id window = one spatial pocket after the grid
            # layout, weights doubled), the traffic plane's actual
            # workload shape. A same-size RANDOM scatter on a graph
            # this small marks every row dirty (each edge's co-optimal
            # cone is a few % of a 2k-node graph; dozens of them union
            # to all of it) — that regime is what the
            # DOS_BUILD_DELTA_MAX_FRAC degrade-to-full guard is for,
            # not what this section measures
            rng = np.random.default_rng(13)
            hot_eids = np.nonzero(gd.src < gd.n // 32)[0]
            eids = rng.choice(hot_eids, size=min(dhot, len(hot_eids)),
                              replace=False)
            fused = os.path.join(ddir, "fused-e000001.diff")
            write_diff(fused, gd.src[eids], gd.dst[eids],
                       gd.w[eids].astype(np.int64) * 2)
            g_ret = _DGraph(gd.xs, gd.ys, gd.src, gd.dst,
                            gd.weights_with_diff(fused))

            fdir = os.path.join(ddir, "full")

            def _reset_full():
                shutil.rmtree(fdir, ignore_errors=True)

            def _full():
                for wid in range(wd):
                    build_worker_shard(g_ret, dcd, wid, fdir,
                                       chunk=512, resume=False)
            _reset_full()
            _, t_fullb = robust_time(_full, reset=_reset_full,
                                     label="delta-full-build")

            edir = epoch_index_dir(ddir, 1)

            def _reset_delta():
                shutil.rmtree(edir, ignore_errors=True)

            rep_box = {}

            def _delta():
                rep_box["rep"] = delta_build_index(gd, dcd, ddir, fused,
                                                   resume=False)
            _reset_delta()
            _, t_deltab = robust_time(_delta, reset=_reset_delta,
                                      label="delta-build")
            rep = rep_box["rep"]
            # correctness gate: the incremental index must be BIT-
            # IDENTICAL to the from-scratch build on the retimed graph
            for f in sorted(os.listdir(fdir)):
                if f.startswith("cpd-"):
                    assert (open(os.path.join(edir, f), "rb").read()
                            == open(os.path.join(fdir, f), "rb").read()
                            ), f"delta block {f} != full rebuild"
            ratio = t_fullb / t_deltab
            log(f"delta build: full {t_fullb:.2f}s vs delta "
                f"{t_deltab:.2f}s -> {ratio:.2f}x "
                f"({rep['rows_recomputed']}/{gd.n} rows recomputed, "
                f"{rep['blocks_skipped']} block(s) byte-copied, "
                f"{rep['changed_edges']} edges changed)")
            delta_stats = {
                "build_delta_nodes": gd.n,
                "build_delta_changed_edges": rep["changed_edges"],
                "build_delta_affected_rows": rep["affected_rows"],
                "build_delta_rows_recomputed": rep["rows_recomputed"],
                "build_delta_skipped_blocks": rep["blocks_skipped"],
                "build_full_seconds": round(t_fullb, 3),
                "build_delta_seconds": round(t_deltab, 3),
                "build_full_rows_per_sec": round(gd.n / t_fullb, 1),
                "build_delta_rows_per_sec": round(gd.n / t_deltab, 1),
                "build_delta_vs_full_ratio": round(ratio, 2),
            }
        finally:
            shutil.rmtree(ddir, ignore_errors=True)

    # ---- weak scaling: same total rows over 1/2/4/8 virtual CPU devices,
    # decomposed into mesh wall-clock (oversubscribed: 8 threads on one
    # core) and per-shard single-device time (the per-chip unit; with
    # zero build collectives, W real chips run shards concurrently)
    weak_stats = {}
    if os.environ.get("BENCH_WEAK", "1") != "0":
        log("weak scaling (virtual CPU mesh subprocess)...")
        weak = _weak_scaling(side=64, chunk=512)
        if weak:
            mesh_s, shard_s = weak["mesh"], weak["shard"]
            sbase = shard_s.get("1")
            log("weak scaling mesh build seconds (1-core host, "
                "oversubscribed): " + ", ".join(
                    f"W={w}: {s}s" for w, s in mesh_s.items()))
            log("weak scaling per-shard device seconds (1 worker's rows "
                "on 1 device): " + ", ".join(
                    f"W={w}: {s}s (x{sbase / s:.2f})"
                    for w, s in shard_s.items()))
            weak_stats = {
                "weak_scaling_build_seconds": mesh_s,
                "weak_scaling_shard_device_seconds": shard_s,
                "weak_scaling_shard_rows": weak["rows"],
            }

    # ---- shard strong scaling on the REAL device: one chip builds
    # worker 0's shard of a W-way partition of the main graph. The build
    # HLO has no collectives (pinned by test), so W chips each holding
    # one such shard would run these same programs CONCURRENTLY: the
    # full-build wall-clock on W chips ≈ this measured per-shard time.
    # This is the positive multi-device evidence available without
    # multi-chip hardware.
    if os.environ.get("BENCH_WEAK", "1") != "0":
        from distributed_oracle_search_tpu.models.cpd import (
            _make_chunk_compute, build_worker_shard,
        )

        shard_dev = {}
        shard_rps = {}
        shard_disp = {}
        shard_comp = {}
        shard_over = {}
        # ONE shared compute context across warm-up, every W, and every
        # rep: DeviceGraph upload + build-kernel resolution are
        # per-process setup a resident worker pays once, and re-paying
        # them per timed rep was per-shard overhead polluting the
        # strong-scaling series (the same hoist as PR 11's ledger one)
        bctx = {}
        warm = tempfile.mkdtemp(prefix="dos-shard-warm-")
        try:  # one warm-up build compiles the chunked program
            build_worker_shard(
                g, DistributionController("tpu", None, 8, g.n), 0, warm,
                chunk=chunk, ctx=bctx)
        finally:
            shutil.rmtree(warm, ignore_errors=True)
        for wsh in (1, 2, 4, 8):
            dcw = DistributionController("tpu", None, wsh, g.n)
            d = tempfile.mkdtemp(prefix=f"dos-shard{wsh}-")
            try:
                # stall-guarded like every build: r04's README headline
                # multiplied an anomalously slow single-shot W=1 reading
                def _reset_sh():      # resume would skip existing blocks
                    shutil.rmtree(d)
                    os.makedirs(d)
                # resume=False: the reset guarantees an empty dir, so
                # the ledger read would be pure timed-region overhead
                _, t_sh_s = robust_time(
                    lambda: build_worker_shard(g, dcw, 0, d, chunk=chunk,
                                               resume=False, ctx=bctx),
                    reset=_reset_sh,
                    # ~2x the best r05 readings per W, default knobs only
                    band_s=({1: 4.0, 2: 2.2, 4: 1.4, 8: 0.9}[wsh]
                            if (width, height) == (96, 96) and chunk == 512
                            else None),
                    label=f"shard-w{wsh}")
                shard_dev[str(wsh)] = round(t_sh_s, 3)
                shard_rps[str(wsh)] = round(dcw.n_owned(0) / t_sh_s, 1)
                # dispatch-vs-compute decomposition of the SAME rows:
                # issue every chunk kernel call without blocking
                # (dispatch = host-side call cost), then block (compute
                # = device wall-clock). total-build minus compute is
                # the per-shard overhead — writer fsyncs, ledger lines,
                # fetch/encode — the series that explains WHY rows/s
                # regresses as the per-shard row count shrinks.
                kind_b, st_b = bctx["kernel"]
                compute = _make_chunk_compute(bctx["dg"], kind_b, st_b, 0)
                owned_w = dcw.owned(0)
                pads_w = []
                for off in range(0, len(owned_w), chunk):
                    part = owned_w[off:off + chunk]
                    pad = np.full(chunk, -1, np.int32)
                    pad[:len(part)] = part
                    pads_w.append(pad)
                t0 = time.perf_counter()
                outs = [compute(p) for p in pads_w]
                t_disp = time.perf_counter() - t0
                jax.block_until_ready([dv for dv, _cd in outs])
                t_comp = time.perf_counter() - t0
                shard_disp[str(wsh)] = round(t_disp, 4)
                shard_comp[str(wsh)] = round(t_comp, 4)
                shard_over[str(wsh)] = round(max(t_sh_s - t_comp, 0.0), 4)
            finally:
                shutil.rmtree(d, ignore_errors=True)
        base = shard_dev["1"]
        log("shard strong scaling (real device, worker-0 shard of a "
            "W-way partition): " + ", ".join(
                f"W={w}: {s}s (x{base / s:.2f})"
                for w, s in shard_dev.items()))
        log("shard strong scaling breakdown (dispatch / compute / "
            "overhead s): " + ", ".join(
                f"W={w}: {shard_disp[w]}/{shard_comp[w]}/{shard_over[w]}"
                for w in shard_dev))
        weak_stats["shard_strong_scaling_device_seconds"] = shard_dev
        weak_stats["shard_strong_scaling_rows_per_sec"] = shard_rps
        weak_stats["shard_strong_scaling_dispatch_seconds"] = shard_disp
        weak_stats["shard_strong_scaling_compute_seconds"] = shard_comp
        weak_stats["shard_strong_scaling_overhead_seconds"] = shard_over
        # scalar twins for the bench-diff gate (it compares numbers,
        # not dicts): the W=1/W=8 endpoints pin the strong-scaling
        # trend so the measured regression cannot silently widen
        weak_stats["shard_strong_scaling_rows_per_sec_w1"] = \
            shard_rps["1"]
        weak_stats["shard_strong_scaling_rows_per_sec_w8"] = \
            shard_rps["8"]
        weak_stats["shard_strong_scaling_overhead_w8_seconds"] = \
            shard_over["8"]

        # sharded streamed serving: two controller processes split one
        # streamed campaign's uploads (each streams only its workers'
        # rows; answers merge via allgather). CPU-mesh subprocesses,
        # like the rest of this section.
        from distributed_oracle_search_tpu.models.cpd import (
            write_index_manifest,
        )
        sdir = tempfile.mkdtemp(prefix="dos-shstream-")
        try:
            gs = synth_city_graph(64, 64, seed=0)
            dcs = DistributionController("mod", 4, 4, gs.n)
            for wid in range(4):
                build_worker_shard(gs, dcs, wid, sdir, chunk=256)
            write_index_manifest(sdir, dcs)
            xys = os.path.join(sdir, "g.xy")
            write_xy(xys, gs.xs, gs.ys, gs.src, gs.dst, gs.w)
            qs = synth_scenario(gs.n, 4096, seed=21)
            qf = os.path.join(sdir, "q.npy")
            np.save(qf, np.asarray(qs))
            log("sharded streamed serving (2 CPU controller "
                "processes)...")
            split = _sharded_stream(xys, sdir, qf)
            if split is None:
                log("sharded streamed subprocess failed; skipping field")
            else:
                tot = sum(split)
                log(f"sharded stream: per-process wire bytes {split} "
                    f"(max share {max(split) / tot:.0%} of "
                    f"{tot / 1e6:.1f} MB total)")
                weak_stats["sharded_stream_bytes_per_process"] = split
                weak_stats["sharded_stream_max_share"] = round(
                    max(split) / tot, 3)
        finally:
            shutil.rmtree(sdir, ignore_errors=True)

    # ---- worker mesh: multi-device sharded execution per device count
    # (lane-mesh build, lane-split walk, on-mesh collective mat) on the
    # 8-virtual-CPU-device shim — parity-asserted inside the
    # subprocess. BENCH_MESH=0 skips.
    mesh_stats = {}
    if os.environ.get("BENCH_MESH", "1") != "0":
        log("worker mesh (1/2/4/8 virtual CPU devices, subprocess)...")
        meshr = _mesh_scaling(side=64, chunk=512)
        if meshr:
            mesh_stats = {
                "mesh_build_rows_per_sec": meshr["build"],
                "mesh_walk_queries_per_sec": meshr["walk"],
                "mesh_mat_rows_per_sec": meshr["mat"],
                # scalar twins for the bench-diff gate (dict keys are
                # not compared); d8 = the full-mesh end of each series
                "mesh_build_rows_per_sec_d8": meshr["build"]["8"],
                "mesh_walk_queries_per_sec_d8": meshr["walk"]["8"],
                "mesh_mat_rows_per_sec_d8": meshr["mat"]["8"],
            }
            for name, series in (("build rows/s", meshr["build"]),
                                 ("walk q/s", meshr["walk"]),
                                 ("mat rows/s", meshr["mat"])):
                log(f"mesh {name} (one time-sliced core — overhead "
                    "proxy, not speedup): " + ", ".join(
                        f"L={k}: {v:,.0f}" for k, v in series.items()))

    # ---- multichip smoke: the full sharded pipeline step on an 8-
    # device (data x worker) mesh — previously a detached
    # MULTICHIP_r*.json dryrun artifact, now a recorded bench section
    # so multichip health rides the same bench-diff gate
    # (multichip_smoke_ok is tolerance-0: any 1 -> 0 drop gates).
    # BENCH_MULTICHIP=0 skips.
    multichip_stats = {}
    if os.environ.get("BENCH_MULTICHIP", "1") != "0":
        log("multichip smoke (dryrun_multichip on 8 virtual CPU "
            "devices)...")
        here = os.path.dirname(os.path.abspath(__file__))
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        try:
            res = subprocess.run(
                [sys.executable, os.path.join(here, "__graft_entry__.py"),
                 "8"], cwd=here, env=env, capture_output=True, text=True,
                timeout=900)
            ok = (res.returncode == 0
                  and "dryrun_multichip OK" in res.stdout)
            tail = (res.stdout or res.stderr).strip().splitlines()
            multichip_stats = {
                "multichip_smoke_ok": 1 if ok else 0,
                "multichip_devices": 8,
                "multichip_tail": tail[-1][:200] if tail else "",
            }
        except (subprocess.TimeoutExpired, OSError) as e:
            log(f"multichip smoke failed to run: {e}")
            multichip_stats = {"multichip_smoke_ok": 0,
                               "multichip_devices": 8,
                               "multichip_tail": str(e)[:200]}
        log(f"multichip smoke: "
            f"{'OK' if multichip_stats['multichip_smoke_ok'] else 'FAIL'}"
            f" ({multichip_stats['multichip_tail']})")

    # ---- online serving: open-loop Poisson load against the serving
    # frontend (serving/) backed by the resident oracle — throughput,
    # p50/p95/p99 latency, cache hit rate on a zipf-skewed workload, and
    # the micro-batcher's realized batch fill. Offered load is set to a
    # fraction of MEASURED closed-loop capacity so the figures are
    # comparable across hosts of very different speed. BENCH_SERVE=0
    # skips.
    serve_stats = {}
    if os.environ.get("BENCH_SERVE", "1") != "0":
        from distributed_oracle_search_tpu.obs import (
            metrics as _serve_obs,
        )
        from distributed_oracle_search_tpu.serving import (
            CallableDispatcher, ServeConfig, ServingFrontend,
        )

        log("online serving (Poisson open loop on the resident "
            "oracle)...")
        sb = int(os.environ.get("BENCH_SERVE_BATCH", 256))
        if sb & (sb - 1):
            # ServeConfig requires a pow2 max_batch (compiled-program
            # reuse is the thing being measured); round up, loudly
            sb2 = 1 << (sb - 1).bit_length()
            log(f"BENCH_SERVE_BATCH={sb} is not a power of two; "
                f"using {sb2}")
            sb = sb2
        sn = int(os.environ.get("BENCH_SERVE_REQUESTS", 10_000))
        util = float(os.environ.get("BENCH_SERVE_UTIL", 0.7))
        rng = np.random.default_rng(17)
        pool = queries[rng.zipf(1.3, size=sn).clip(1, len(queries)) - 1]

        def _oracle_dispatch(wid, q, rconf, diff):
            return oracle.query(q)

        # closed-loop capacity: saturate the frontend (submit everything
        # at once) to measure what the shards can actually drain
        sconf = ServeConfig(queue_depth=max(sn, 1024), max_batch=sb,
                            max_wait_ms=2.0, deadline_ms=600_000.0,
                            cache_bytes=0).validate()
        fe = ServingFrontend(dc, CallableDispatcher(_oracle_dispatch),
                             sconf=sconf)
        fe.start()
        for b in (1, sb // 4, sb):            # warm the program shapes
            fe_futs = [fe.submit(int(s), int(t))
                       for s, t in queries[:b]]
            for f in fe_futs:
                f.result(600)
        t0 = time.perf_counter()
        futs = [fe.submit(int(s), int(t)) for s, t in pool]
        for f in futs:
            f.result(600)
        cap_s = time.perf_counter() - t0
        fe.stop()
        capacity_qps = sn / cap_s
        log(f"serve capacity (closed loop): {sn} in {cap_s:.2f}s -> "
            f"{capacity_qps:,.0f} q/s")

        # open loop at util * capacity, cache ON (the skewed workload's
        # steady state), latency measured request-by-request against the
        # Poisson arrival clock
        offered = capacity_qps * util
        snap0 = _serve_obs.REGISTRY.snapshot()
        fe = ServingFrontend(dc, CallableDispatcher(_oracle_dispatch),
                             sconf=ServeConfig(
                                 queue_depth=4096, max_batch=sb,
                                 max_wait_ms=2.0,
                                 deadline_ms=60_000.0).validate())
        fe.start()
        arrivals = np.cumsum(rng.exponential(1.0 / offered, size=sn))
        t0 = time.perf_counter()
        mono0 = time.monotonic()
        futs = []
        for (s, t), at in zip(pool, arrivals):
            now = time.perf_counter() - t0
            if at > now:
                time.sleep(at - now)
            futs.append(fe.submit(int(s), int(t)))
        results = [f.result(600) for f in futs]
        wall_s = time.perf_counter() - t0
        fe.stop()
        lat_ms = (np.array([r.t_done for r in results])
                  - (mono0 + arrivals)) * 1e3
        ok = np.array([r.ok for r in results])
        snap1 = _serve_obs.REGISTRY.snapshot()

        def _cdelta(name):
            return (snap1["counters"].get(name, 0)
                    - snap0["counters"].get(name, 0))

        fill0 = snap0["histograms"]["serve_batch_fill"]
        fill1 = snap1["histograms"]["serve_batch_fill"]
        nb = fill1["count"] - fill0["count"]
        mean_fill = (fill1["sum"] - fill0["sum"]) / max(nb, 1)
        hits = _cdelta("serve_cache_hits_total")
        misses = _cdelta("serve_cache_misses_total")
        # an all-shed/all-error drill must degrade the figures, not
        # crash the run after every earlier section's work
        p50, p95, p99 = ((float(np.percentile(lat_ms[ok], q))
                          for q in (50, 95, 99)) if ok.any()
                         else (float("nan"),) * 3)
        serve_stats = {
            "serve_capacity_queries_per_sec": round(capacity_qps, 1),
            "serve_offered_queries_per_sec": round(offered, 1),
            "serve_queries_per_sec": round(int(ok.sum()) / wall_s, 1),
            "serve_p50_ms": round(p50, 3),
            "serve_p95_ms": round(p95, 3),
            "serve_p99_ms": round(p99, 3),
            "serve_shed": int(len(results) - ok.sum()),
            "serve_cache_hit_rate": round(hits / max(hits + misses, 1),
                                          3),
            "serve_mean_batch_fill": round(mean_fill, 1),
            "serve_batches": int(nb),
        }
        log(f"serve open loop at {offered:,.0f} q/s offered: "
            f"{serve_stats['serve_queries_per_sec']:,.0f} q/s served, "
            f"p50/p95/p99 {p50:.2f}/{p95:.2f}/{p99:.2f} ms, "
            f"cache hit rate {serve_stats['serve_cache_hit_rate']:.0%}, "
            f"mean batch fill {mean_fill:.1f}, "
            f"shed {serve_stats['serve_shed']}")

    # ---- transport section: the streaming RPC data plane vs the FIFO
    # wire, head to head on the SAME worker, engine, and workload —
    # per-batch dispatch overhead (wall minus pure engine time), p99,
    # and throughput for each lane. One in-thread FifoServer serves
    # both transports (the FIFO loop and the socket accept loop share
    # the engine), so the delta is pure transport cost: query-file
    # write + bash transfer script + two FIFO rendezvous + results
    # sidecar read vs one frame round-trip. BENCH_RPC=0 skips.
    rpc_stats = {}
    if os.environ.get("BENCH_RPC", "1") != "0":
        import threading as _threading

        import distributed_oracle_search_tpu.serving.dispatch as _dmod
        from distributed_oracle_search_tpu.data import (
            ensure_synth_dataset, read_scen,
        )
        from distributed_oracle_search_tpu.data.graph import Graph
        from distributed_oracle_search_tpu.models.cpd import (
            build_worker_shard, write_index_manifest,
        )
        from distributed_oracle_search_tpu.serving import (
            FifoDispatcher, RpcDispatcher,
        )
        from distributed_oracle_search_tpu.transport.wire import (
            RuntimeConfig,
        )
        from distributed_oracle_search_tpu.utils.config import (
            ClusterConfig,
        )
        from distributed_oracle_search_tpu.worker import (
            FifoServer, stop_server,
        )
        from distributed_oracle_search_tpu.worker.server import (
            RpcServeLoop,
        )

        log("transport (rpc vs fifo dispatch, one worker, same "
            "workload)...")
        tdir = tempfile.mkdtemp(prefix="bench-rpc-")
        _old_sockdir = os.environ.get("DOS_RPC_SOCKET_DIR")
        os.environ["DOS_RPC_SOCKET_DIR"] = tdir
        tpaths = ensure_synth_dataset(tdir, width=24, height=18,
                                      n_queries=512, seed=37)
        tconf = ClusterConfig(
            workers=["localhost"], partmethod="mod", partkey=1,
            outdir=os.path.join(tdir, "index"), xy_file=tpaths["xy"],
            scenfile=tpaths["scen"], nfs=tdir).validate()
        tg = Graph.from_xy(tconf.xy_file)
        tdc = DistributionController("mod", 1, 1, tg.n)
        build_worker_shard(tg, tdc, 0, tconf.outdir)
        write_index_manifest(tconf.outdir, tdc)
        tqueries = read_scen(tconf.scenfile)
        tfifo = os.path.join(tdir, "worker0.fifo")
        tsrv = FifoServer(tconf, 0, command_fifo=tfifo)
        tth = _threading.Thread(target=tsrv.serve_forever, daemon=True)
        tth.start()
        for _ in range(200):
            if os.path.exists(tfifo):
                break
            time.sleep(0.02)
        tloop = RpcServeLoop(tsrv).start()
        nb = int(os.environ.get("BENCH_RPC_BATCHES", 48))
        bsz = int(os.environ.get("BENCH_RPC_BATCH", 64))
        tbatches = [tqueries[(i * bsz) % len(tqueries):][:bsz]
                    for i in range(nb)]
        tbatches = [b if len(b) == bsz else tqueries[:bsz]
                    for b in tbatches]
        trc = RuntimeConfig()
        fifo_disp = FifoDispatcher(tconf, timeout=120.0)
        rpc_disp = RpcDispatcher(tconf, timeout=120.0)
        orig_cfp = _dmod.command_fifo_path
        _dmod.command_fifo_path = lambda wid: tfifo
        try:
            # warm every lane + the engine's compiled programs off the
            # clock (a mid-run XLA compile would charge one transport)
            fifo_disp.answer_batch(0, tbatches[0], trc, "-")
            rpc_disp.answer_batch(0, tbatches[0], trc, "-")
            tsrv.engine.answer(tbatches[0], trc, "-")

            def _drive(step):
                lat = []
                t0 = time.perf_counter()
                for b in tbatches:
                    s = time.perf_counter()
                    step(b)
                    lat.append(time.perf_counter() - s)
                return time.perf_counter() - t0, np.array(lat)

            eng_wall, eng_lat = _drive(
                lambda b: tsrv.engine.answer(b, trc, "-"))
            rpc_wall, rpc_lat = _drive(
                lambda b: rpc_disp.answer_batch(0, b, trc, "-"))
            fifo_wall, fifo_lat = _drive(
                lambda b: fifo_disp.answer_batch(0, b, trc, "-"))
        finally:
            _dmod.command_fifo_path = orig_cfp
            rpc_disp.close()
            fifo_disp.close()
            stop_server(tfifo, deadline_s=5.0)
            tth.join(timeout=15)
            tloop.stop()
            shutil.rmtree(tdir, ignore_errors=True)
            # restore the socket-dir knob: a later section's supervisor
            # must not resolve sockets under the deleted temp dir
            if _old_sockdir is None:
                os.environ.pop("DOS_RPC_SOCKET_DIR", None)
            else:
                os.environ["DOS_RPC_SOCKET_DIR"] = _old_sockdir
        eng_ms = float(eng_lat.mean() * 1e3)
        rpc_over = float(max(rpc_lat.mean() * 1e3 - eng_ms, 1e-3))
        fifo_over = float(max(fifo_lat.mean() * 1e3 - eng_ms, 1e-3))
        rpc_stats = {
            # per-batch dispatch OVERHEAD: mean wall minus the pure
            # engine time for the identical batch sequence
            "serve_rpc_dispatch_ms": round(rpc_over, 3),
            "serve_fifo_dispatch_ms": round(fifo_over, 3),
            "serve_rpc_vs_fifo_dispatch_ratio": round(
                fifo_over / rpc_over, 2),
            "serve_rpc_p99_ms": round(
                float(np.percentile(rpc_lat, 99)) * 1e3, 3),
            "serve_fifo_p99_ms": round(
                float(np.percentile(fifo_lat, 99)) * 1e3, 3),
            "serve_rpc_queries_per_sec": round(
                nb * bsz / rpc_wall, 1),
            "serve_fifo_queries_per_sec": round(
                nb * bsz / fifo_wall, 1),
        }
        log(f"transport: engine {eng_ms:.2f} ms/batch; rpc overhead "
            f"{rpc_over:.2f} ms/batch "
            f"(p99 {rpc_stats['serve_rpc_p99_ms']:.1f} ms), fifo "
            f"overhead {fifo_over:.2f} ms/batch "
            f"(p99 {rpc_stats['serve_fifo_p99_ms']:.1f} ms) -> "
            f"ratio {rpc_stats['serve_rpc_vs_fifo_dispatch_ratio']}x, "
            f"{rpc_stats['serve_rpc_queries_per_sec']:,.0f} vs "
            f"{rpc_stats['serve_fifo_queries_per_sec']:,.0f} q/s")

    # ---- gateway tier section: rush hour on the binary client
    # protocol — two stateless frontend replicas over the SAME worker
    # (gateway/ frames, credit windows, per-replica L1 + shard-owner
    # L2) vs the single-head line-protocol serve on one zipf-skewed
    # pool. Reports aggregate q/s, per-frontend fairness (max/min),
    # and the fleet's two-level cache hit rate vs the single head's;
    # answers must be bit-identical between the lanes. BENCH_GATEWAY=0
    # skips.
    gateway_stats = {}
    if os.environ.get("BENCH_GATEWAY", "1") != "0":
        import queue as _gqueue
        import socket as _gsocket
        import threading as _gthreading

        from distributed_oracle_search_tpu.data import (
            ensure_synth_dataset, read_scen,
        )
        from distributed_oracle_search_tpu.data.graph import Graph
        from distributed_oracle_search_tpu.gateway import (
            DosClient, GatewayConfig, GatewayTier,
        )
        from distributed_oracle_search_tpu.gateway import (
            client as gateway_client,
        )
        from distributed_oracle_search_tpu.models.cpd import (
            build_worker_shard, write_index_manifest,
        )
        from distributed_oracle_search_tpu.serving import (
            RpcDispatcher, ServeConfig, ServingFrontend,
        )
        from distributed_oracle_search_tpu.serving import ingress
        from distributed_oracle_search_tpu.transport.wire import (
            RuntimeConfig,
        )
        from distributed_oracle_search_tpu.utils.config import (
            ClusterConfig,
        )
        from distributed_oracle_search_tpu.worker import (
            FifoServer, stop_server,
        )
        from distributed_oracle_search_tpu.worker.server import (
            RpcServeLoop,
        )

        log("gateway tier (2 binary-protocol frontends vs single-head "
            "line protocol, same worker)...")
        gdir = tempfile.mkdtemp(prefix="bench-gw-")
        _genv = {k: os.environ.get(k) for k in
                 ("DOS_RPC_SOCKET_DIR", "DOS_GATEWAY_L2_BYTES")}
        os.environ["DOS_RPC_SOCKET_DIR"] = gdir
        os.environ["DOS_GATEWAY_L2_BYTES"] = str(1 << 20)
        gpaths = ensure_synth_dataset(gdir, width=24, height=18,
                                      n_queries=512, seed=41)
        gcfg = ClusterConfig(
            workers=["localhost"], partmethod="mod", partkey=1,
            outdir=os.path.join(gdir, "index"), xy_file=gpaths["xy"],
            scenfile=gpaths["scen"], nfs=gdir).validate()
        gg = Graph.from_xy(gcfg.xy_file)
        gdc = DistributionController("mod", 1, 1, gg.n)
        build_worker_shard(gg, gdc, 0, gcfg.outdir)
        write_index_manifest(gcfg.outdir, gdc)
        gqueries = read_scen(gcfg.scenfile)
        gfifo = os.path.join(gdir, "gw-worker0.fifo")
        gwsrv = FifoServer(gcfg, 0, command_fifo=gfifo)
        gwth = _gthreading.Thread(target=gwsrv.serve_forever,
                                  daemon=True)
        gwth.start()
        for _ in range(200):
            if os.path.exists(gfifo):
                break
            time.sleep(0.02)
        gloop = RpcServeLoop(gwsrv).start()
        grc = RuntimeConfig()
        gn = int(os.environ.get("BENCH_GATEWAY_REQUESTS", 4096))
        gb = int(os.environ.get("BENCH_GATEWAY_BATCH", 64))
        grng = np.random.default_rng(23)
        gpool = gqueries[grng.zipf(1.3, size=gn)
                         .clip(1, len(gqueries)) - 1]
        # warm the worker engine's compiled shapes off every clock
        gwsrv.engine.answer(gqueries[:gb], grc, "-")

        def _gfe():
            fe = ServingFrontend(
                gdc, RpcDispatcher(gcfg, timeout=120.0),
                sconf=ServeConfig(queue_depth=max(gn, 1024),
                                  max_batch=gb, max_wait_ms=2.0,
                                  deadline_ms=600_000.0,
                                  cache_bytes=1 << 20).validate())
            fe.start()
            return fe

        def _line_row(line):
            # OK <s> <t> <cost> <plen> <finished> [cached]
            toks = line.split()
            if len(toks) >= 6 and toks[0] == "OK":
                return (toks[0], int(toks[3]), int(toks[4]),
                        bool(int(toks[5])))
            return (toks[0] if toks else "ERROR", -1, -1, False)

        gclients = []
        tier = None
        gfes = []
        try:
            # -- single head: the legacy line-protocol lane, fully
            # pipelined (writer thread keeps lines flowing while the
            # replies stream back in order)
            fe0 = _gfe()
            gfes.append(fe0)
            glsock = os.path.join(gdir, "line.sock")
            glstop = _gthreading.Event()
            glth = _gthreading.Thread(
                target=ingress.serve_unix_socket, args=(fe0, glsock),
                kwargs={"stop": glstop}, daemon=True)
            glth.start()
            for _ in range(200):
                if os.path.exists(glsock):
                    break
                time.sleep(0.02)
            gcs = _gsocket.socket(_gsocket.AF_UNIX,
                                  _gsocket.SOCK_STREAM)
            gcs.connect(glsock)
            gcrf = gcs.makefile("r")
            gcwf = gcs.makefile("w")

            def _drive_line(part):
                def _pump():
                    for s, t in part:
                        gcwf.write(f"{int(s)} {int(t)}\n")
                    gcwf.flush()

                rows = []
                w = _gthreading.Thread(target=_pump, daemon=True)
                t0 = time.perf_counter()
                w.start()
                for _ in range(len(part)):
                    rows.append(_line_row(gcrf.readline()))
                w.join()
                return time.perf_counter() - t0, rows

            _drive_line(gpool[:gb])          # warm lane + L1 + shapes
            h0, m0 = fe0.cache.hits, fe0.cache.misses
            l2h0, l2m0 = gwsrv.l2.hits, gwsrv.l2.misses
            single_wall, base_rows = _drive_line(gpool)
            single_hits = ((fe0.cache.hits - h0)
                           + (gwsrv.l2.hits - l2h0))
            gcwf.write("quit\n")
            gcwf.flush()
            gcs.close()
            glstop.set()
            glth.join(timeout=10)
            fe0.stop()

            # -- the tier: 2 replicas, 2 clients, batched query frames.
            # The single head's L2 entries are flushed first — the
            # fleet hit rate must be earned by THIS lane's traffic
            gwsrv.l2.invalidate()
            gfes = [fe0] + [_gfe() for _ in range(2)]
            fes = gfes[1:]
            ggconf = GatewayConfig(
                replicas=2, socket_dir=gdir, credit=64,
                deadline_ms=600_000.0).validate()
            tier = GatewayTier([(fe, None) for fe in fes],
                               gconf=ggconf).start()
            gclients = [DosClient(ep) for ep in tier.endpoints]
            ghalves = [gpool[0::2], gpool[1::2]]
            for c, half in zip(gclients, ghalves):   # warm, off-clock
                c.query_batch([(int(s), int(t)) for s, t in half[:gb]],
                              timeout=600.0)
            gh0 = [(fe.cache.hits, fe.cache.misses) for fe in fes]
            gl2h0 = gwsrv.l2.hits
            gwalls = [0.0, 0.0]
            grows = [[], []]

            def _drive_gw(k):
                # open loop: a pump thread keeps the credit window
                # full while this thread collects replies in
                # submission order — the frame-level twin of the line
                # lane's pipelined writer
                c, half = gclients[k], ghalves[k]
                fidq = _gqueue.Queue()

                def _pump():
                    for i in range(0, len(half), gb):
                        batch = [(int(s), int(t))
                                 for s, t in half[i:i + gb]]
                        fidq.put(c.submit_pairs(batch, timeout=600.0))
                    fidq.put(None)

                t0 = time.perf_counter()
                w = _gthreading.Thread(target=_pump, daemon=True)
                w.start()
                while True:
                    fid = fidq.get()
                    if fid is None:
                        break
                    rows = gateway_client.pair_rows(
                        c.wait(fid, timeout=600.0))
                    grows[k].extend((st, cost, plen, fin) for st, cost,
                                    plen, fin, _cached in rows)
                w.join()
                gwalls[k] = time.perf_counter() - t0

            gths = [_gthreading.Thread(target=_drive_gw, args=(k,))
                    for k in range(2)]
            t0 = time.perf_counter()
            for th in gths:
                th.start()
            for th in gths:
                th.join()
            tier_wall = time.perf_counter() - t0
            fleet_hits = (sum(fe.cache.hits - h for fe, (h, _m)
                              in zip(fes, gh0))
                          + (gwsrv.l2.hits - gl2h0))
        finally:
            for c in gclients:
                c.close()
            if tier is not None:
                tier.stop()
            for fe in gfes[1:]:
                fe.stop()
            stop_server(gfifo, deadline_s=5.0)
            gwth.join(timeout=15)
            gloop.stop()
            shutil.rmtree(gdir, ignore_errors=True)
            for k, v in _genv.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        gw_rows = [None] * gn
        gw_rows[0::2] = grows[0]
        gw_rows[1::2] = grows[1]
        matches = sum(a == b for a, b in zip(base_rows, gw_rows))
        per_fe_qps = [len(h) / max(w, 1e-9)
                      for h, w in zip(ghalves, gwalls)]
        gateway_stats = {
            "gateway_aggregate_queries_per_sec": round(
                gn / tier_wall, 1),
            "gateway_single_head_queries_per_sec": round(
                gn / single_wall, 1),
            "gateway_vs_single_head_ratio": round(
                single_wall / tier_wall, 2),
            "gateway_fairness_ratio": round(
                max(per_fe_qps) / max(min(per_fe_qps), 1e-9), 2),
            "gateway_answers_match": round(matches / gn, 4),
            "gateway_fleet_cache_hit_rate": round(fleet_hits / gn, 3),
            "gateway_single_head_cache_hit_rate": round(
                single_hits / gn, 3),
        }
        log(f"gateway: tier "
            f"{gateway_stats['gateway_aggregate_queries_per_sec']:,.0f}"
            f" q/s vs single head "
            f"{gateway_stats['gateway_single_head_queries_per_sec']:,.0f}"
            f" q/s ({gateway_stats['gateway_vs_single_head_ratio']}x), "
            f"fairness {gateway_stats['gateway_fairness_ratio']}x, "
            f"answers match {gateway_stats['gateway_answers_match']:.2%}"
            f", fleet cache "
            f"{gateway_stats['gateway_fleet_cache_hit_rate']:.0%} vs "
            f"single "
            f"{gateway_stats['gateway_single_head_cache_hit_rate']:.0%}")

    # ---- gateway HA section: the partition chaos drill priced as a
    # bench — one HA client (registry discovery) drives an open-loop
    # burst over a 3-frontend leased tier while one frontend is killed
    # abruptly and a second goes half-open (blackhole-conn). The
    # contract under test: zero lost accepted requests, zero duplicate
    # answers (resubmission dedup), and failover recovery bounded by
    # the detection timeout + reconnect. BENCH_GATEWAY_HA=0 skips.
    gateway_ha_stats = {}
    if os.environ.get("BENCH_GATEWAY_HA", "1") != "0":
        import queue as _hqueue
        import socket as _hsocket  # noqa: F401 — parity with gw block
        import threading as _hthreading

        from distributed_oracle_search_tpu.data import (
            ensure_synth_dataset, read_scen,
        )
        from distributed_oracle_search_tpu.data.graph import Graph
        from distributed_oracle_search_tpu.gateway import (
            DosClient, GatewayConfig, GatewayRegistry, GatewayTier,
        )
        from distributed_oracle_search_tpu.gateway import (
            client as gateway_client,
        )
        from distributed_oracle_search_tpu.models.cpd import (
            build_worker_shard, write_index_manifest,
        )
        from distributed_oracle_search_tpu.serving import (
            RpcDispatcher, ServeConfig, ServingFrontend,
        )
        from distributed_oracle_search_tpu.testing import faults
        from distributed_oracle_search_tpu.transport.frames import (
            TransportError,
        )
        from distributed_oracle_search_tpu.transport.wire import (
            RuntimeConfig,
        )
        from distributed_oracle_search_tpu.utils.config import (
            ClusterConfig,
        )
        from distributed_oracle_search_tpu.worker import (
            FifoServer, stop_server,
        )
        from distributed_oracle_search_tpu.worker.server import (
            RpcServeLoop,
        )

        log("gateway HA (kill + blackhole mid-burst over a 3-frontend "
            "leased tier, one failover client)...")
        hdir = tempfile.mkdtemp(prefix="bench-gwha-")
        _henv = {k: os.environ.get(k) for k in
                 ("DOS_RPC_SOCKET_DIR", "DOS_FAULTS")}
        os.environ["DOS_RPC_SOCKET_DIR"] = hdir
        os.environ.pop("DOS_FAULTS", None)
        hpaths = ensure_synth_dataset(hdir, width=16, height=12,
                                      n_queries=256, seed=47)
        hcfg = ClusterConfig(
            workers=["localhost"], partmethod="mod", partkey=1,
            outdir=os.path.join(hdir, "index"), xy_file=hpaths["xy"],
            scenfile=hpaths["scen"], nfs=hdir).validate()
        hg = Graph.from_xy(hcfg.xy_file)
        hdc = DistributionController("mod", 1, 1, hg.n)
        build_worker_shard(hg, hdc, 0, hcfg.outdir)
        write_index_manifest(hcfg.outdir, hdc)
        hqueries = read_scen(hcfg.scenfile)
        hfifo = os.path.join(hdir, "ha-worker0.fifo")
        hwsrv = FifoServer(hcfg, 0, command_fifo=hfifo)
        hwth = _hthreading.Thread(target=hwsrv.serve_forever,
                                  daemon=True)
        hwth.start()
        for _ in range(200):
            if os.path.exists(hfifo):
                break
            time.sleep(0.02)
        hloop = RpcServeLoop(hwsrv).start()
        hrc = RuntimeConfig()
        hn = int(os.environ.get("BENCH_GATEWAY_HA_REQUESTS", 2048))
        hb = int(os.environ.get("BENCH_GATEWAY_HA_BATCH", 64))
        hrng = np.random.default_rng(29)
        hpool = hqueries[hrng.zipf(1.3, size=hn)
                         .clip(1, len(hqueries)) - 1]
        hwsrv.engine.answer(hqueries[:hb], hrc, "-")   # warm shapes

        def _hfe():
            fe = ServingFrontend(
                hdc, RpcDispatcher(hcfg, timeout=120.0),
                sconf=ServeConfig(queue_depth=max(hn, 1024),
                                  max_batch=hb, max_wait_ms=2.0,
                                  deadline_ms=600_000.0,
                                  cache_bytes=0).validate())
            fe.start()
            return fe

        hclient = None
        htier = None
        hfes = []
        try:
            hfes = [_hfe() for _ in range(3)]
            hreg = GatewayRegistry(os.path.join(hdir, "reg"),
                                   lease_s=1.0)
            hgconf = GatewayConfig(
                replicas=3, socket_dir=hdir, credit=64,
                deadline_ms=600_000.0, lease_s=1.0).validate()
            htier = GatewayTier([(fe, None) for fe in hfes],
                                gconf=hgconf, registry=hreg).start()
            # fault-free baseline over the SAME pool: the drill's
            # answers must be bit-identical to these rows
            hbase_client = DosClient(htier.endpoints[2])
            hbase_rows = []
            for i in range(0, hn, hb):
                hbase_rows.extend(
                    (st, cost, plen, fin) for st, cost, plen, fin,
                    _c in hbase_client.query_batch(
                        [(int(s), int(t)) for s, t in hpool[i:i + hb]],
                        timeout=600.0))
            hbase_client.close()

            hclient = DosClient(registry_dir=hreg.dir)   # discovery
            nbatches = (hn + hb - 1) // hb
            kill_at, hole_at = nbatches // 3, (2 * nbatches) // 3
            hfidq = _hqueue.Queue()

            def _hpump():
                try:
                    for bi in range(nbatches):
                        if bi == kill_at:
                            # abrupt death: lease left to expire
                            htier.servers[0].stop(graceful=False)
                        if bi == hole_at:
                            # half-open partition on whichever
                            # frontend the client failed over to (f1,
                            # next in discovery order)
                            os.environ["DOS_FAULTS"] = \
                                "blackhole-conn;wid=1;times=inf"
                            faults.reset()
                        batch = [
                            (int(s), int(t))
                            for s, t in hpool[bi * hb:(bi + 1) * hb]]
                        hfidq.put((bi, hclient.submit_pairs(
                            batch, timeout=600.0),
                            time.perf_counter()))
                finally:
                    hfidq.put(None)

            hrows_by_batch = {}
            hlat_ms = []
            hw = _hthreading.Thread(target=_hpump, daemon=True)
            hw.start()
            while True:
                item = hfidq.get()
                if item is None:
                    break
                bi, fid, t_sub = item
                give_up = time.perf_counter() + 120.0
                got = None
                while got is None:
                    try:
                        got = gateway_client.pair_rows(
                            hclient.wait(fid, timeout=2.0))
                    except TimeoutError:
                        # wait's own timeout already failed the
                        # client over and resubmitted; re-wait
                        # collects the replayed answer
                        if time.perf_counter() > give_up:
                            break
                    except TransportError:
                        break
                if got is None:
                    continue
                hlat_ms.append((time.perf_counter() - t_sub) * 1e3)
                hrows_by_batch[bi] = [(st, cost, plen, fin)
                                      for st, cost, plen, fin, _c
                                      in got]
            hw.join()
            # per-batch accounting so a dropped batch can't misalign
            # the comparison: a never-answered request is lost; an
            # answered-but-wrong request counts as lost too (the HA
            # contract is bit-identical answers, tolerance 0)
            hlost = 0
            hmatch = 0
            for bi in range(nbatches):
                base = hbase_rows[bi * hb:(bi + 1) * hb]
                rows = hrows_by_batch.get(bi)
                if rows is None:
                    hlost += len(base)
                    continue
                ok = sum(a == b for a, b in zip(base, rows))
                hmatch += ok
                hlost += len(base) - ok
            hp99 = (float(np.percentile(np.asarray(hlat_ms), 99))
                    if hlat_ms else float("nan"))
            gateway_ha_stats = {
                "gateway_ha_lost_requests": int(hlost),
                "gateway_ha_duplicate_answers": int(hclient.unmatched),
                "gateway_ha_failover_p99_ms": round(hp99, 1),
            }
            log(f"gateway HA: lost "
                f"{gateway_ha_stats['gateway_ha_lost_requests']}, "
                f"duplicates "
                f"{gateway_ha_stats['gateway_ha_duplicate_answers']}, "
                f"p99 {gateway_ha_stats['gateway_ha_failover_p99_ms']}"
                f" ms across {hclient.failovers} failover(s), "
                f"answers match {hmatch}/{hn}")
        finally:
            if hclient is not None:
                hclient.close()
            os.environ.pop("DOS_FAULTS", None)
            faults.reset()
            if htier is not None:
                htier.stop()
            for fe in hfes:
                fe.stop()
            stop_server(hfifo, deadline_s=5.0)
            hwth.join(timeout=15)
            hloop.stop()
            shutil.rmtree(hdir, ignore_errors=True)
            for k, v in _henv.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    # ---- telemetry section: the fleet telemetry bus priced in
    # isolation — publish-side tick cost (what the bus adds to every
    # resident process each DOS_TELEMETRY_INTERVAL_S; the acceptance
    # bar is overhead < 1% of the interval) and the head's ingest rate
    # into the ring store (decode + seq dedupe + delta clamp + store
    # appends per tick). In-process on purpose: the wire itself is the
    # transport section's story — this prices the bus machinery on the
    # REAL registry this bench run populated (hundreds of live series,
    # the fleet-realistic key count). BENCH_TELEMETRY=0 skips.
    telemetry_stats = {}
    if os.environ.get("BENCH_TELEMETRY", "1") != "0":
        from distributed_oracle_search_tpu.obs import (
            telemetry as _tele,
        )
        from distributed_oracle_search_tpu.obs import (
            timeseries as _tts,
        )

        log("telemetry (publish overhead + head ingest rate)...")
        n_ticks = int(os.environ.get("BENCH_TELEMETRY_TICKS", 400))
        pub = _tele.TelemetryPublisher("bench", sinks=[])
        pub.tick_once()               # first tick is full — warm it
        tick_s = []
        for _ in range(n_ticks):
            s = time.perf_counter()
            pub.tick_once()
            tick_s.append(time.perf_counter() - s)
        tick_s = np.array(tick_s)
        # head side: replay encoded ticks (the wire's view) from 8
        # simulated sources into a fresh store — per-source seqs
        # strictly increase, so every tick is accepted, none deduped
        tstore = _tts.TimeseriesStore()
        tingest = _tele.TelemetryIngest(tstore)
        wire_ticks = []
        for i in range(n_ticks):
            t = dict(pub.tick_once(),
                     source=f"bench-w{i % 8}", seq=i // 8)
            wire_ticks.append(_tele.encode_tick(t))
        s = time.perf_counter()
        accepted = sum(tingest.ingest(t) for t in wire_ticks)
        ingest_wall = max(time.perf_counter() - s, 1e-9)
        interval = max(pub.interval, 1e-3)
        telemetry_stats = {
            "telemetry_publish_p99_ms": round(
                float(np.percentile(tick_s, 99)) * 1e3, 3),
            # mean tick cost / publish cadence: the fraction of every
            # resident process the bus consumes (acceptance: < 0.01)
            "telemetry_publish_overhead_frac": round(
                float(tick_s.mean()) / interval, 6),
            "telemetry_head_ingest_per_sec": round(
                accepted / ingest_wall, 1),
        }
        log(f"telemetry: publish "
            f"{float(tick_s.mean()) * 1e3:.3f} ms/tick mean "
            f"(p99 {telemetry_stats['telemetry_publish_p99_ms']:.3f} "
            f"ms) = {telemetry_stats['telemetry_publish_overhead_frac']:.4%} "
            f"of the {interval:.0f}s cadence; head ingest "
            f"{telemetry_stats['telemetry_head_ingest_per_sec']:,.0f} "
            f"ticks/s ({accepted}/{n_ticks} accepted)")

    # ---- replication section: failover throughput/latency with a
    # killed primary, and hedge win rate under an injected delay fault.
    # A small dedicated 2-worker R=2 host-style world (block files +
    # EngineDispatcher) — the figures characterize the routing layer,
    # not the kernels, so a small graph keeps it honest and cheap.
    # BENCH_REPL=0 skips.
    repl_stats = {}
    if os.environ.get("BENCH_REPL", "1") != "0":
        from distributed_oracle_search_tpu.data import (
            ensure_synth_dataset, read_scen,
        )
        from distributed_oracle_search_tpu.data.graph import Graph
        from distributed_oracle_search_tpu.models.cpd import (
            build_replica_shards, build_worker_shard,
            write_index_manifest,
        )
        from distributed_oracle_search_tpu.obs import (
            metrics as _robs,
        )
        from distributed_oracle_search_tpu.serving import (
            EngineDispatcher, HedgeConfig, ServeConfig, ServingFrontend,
        )
        from distributed_oracle_search_tpu.transport import resilience
        from distributed_oracle_search_tpu.transport.wire import (
            RuntimeConfig,
        )
        from distributed_oracle_search_tpu.utils.config import (
            ClusterConfig,
        )

        def _rc(name):
            return _robs.REGISTRY.snapshot()["counters"].get(name, 0)

        log("replication (failover + hedged dispatch drills)...")
        rdir = tempfile.mkdtemp(prefix="bench-repl-")
        rpaths = ensure_synth_dataset(rdir, width=24, height=18,
                                      n_queries=512, seed=31)
        rconf_c = ClusterConfig(
            workers=["localhost"] * 2, partmethod="mod", partkey=2,
            outdir=os.path.join(rdir, "index"),
            xy_file=rpaths["xy"], scenfile=rpaths["scen"], nfs=rdir,
            replication=2).validate()
        rg = Graph.from_xy(rconf_c.xy_file)
        rdc = DistributionController("mod", 2, 2, rg.n, replication=2)
        for wid in range(2):
            build_worker_shard(rg, rdc, wid, rconf_c.outdir)
            build_replica_shards(rg, rdc, wid, rconf_c.outdir)
        write_index_manifest(rconf_c.outdir, rdc)
        rqueries = read_scen(rconf_c.scenfile)
        rn = int(os.environ.get("BENCH_REPL_REQUESTS", 512))
        pool = rqueries[np.arange(rn) % len(rqueries)]
        rrconf = RuntimeConfig()
        disp = EngineDispatcher(rconf_c, graph=rg, dc=rdc)
        # warm every engine (primary + replica lanes) off the clock
        for wid in range(2):
            mine = rqueries[rdc.worker_of(rqueries[:, 1]) == wid][:64]
            disp.answer_batch(wid, mine, rrconf, "-")
            disp.answer_batch(wid, mine, rrconf, "-",
                              via=(wid + 1) % 2)

        def _drill(registry, hconf, tag):
            """Closed-loop drill: submit the pool, wait for every
            answer; per-request latency measured submit -> t_done."""
            fe = ServingFrontend(
                rdc, disp,
                sconf=ServeConfig(max_batch=64, max_wait_ms=2.0,
                                  queue_depth=max(rn, 1024),
                                  cache_bytes=0,
                                  deadline_ms=600_000.0),
                registry=registry, hconf=hconf)
            fe.start()
            t0 = time.perf_counter()
            submits, futs = [], []
            for s, t in pool:
                submits.append(time.monotonic())
                futs.append(fe.submit(int(s), int(t)))
            res = [f.result(600) for f in futs]
            wall = time.perf_counter() - t0
            fe.stop()
            n_ok = sum(r.ok for r in res)
            lat_ms = [(r.t_done - ts) * 1e3
                      for r, ts in zip(res, submits) if r.ok]
            p99 = float(np.percentile(lat_ms, 99)) if lat_ms else float(
                "nan")
            log(f"  {tag}: {n_ok}/{rn} ok in {wall:.2f}s "
                f"({n_ok / wall:,.0f} q/s, p99 {p99:.1f} ms)")
            return n_ok, wall, p99

        # clean baseline (no failures, hedging off)
        ok_clean, wall_clean, p99_clean = _drill(
            None, HedgeConfig(enabled=False), "clean")
        # failover: worker 0's breaker forced OPEN — every shard-0
        # batch re-routes to worker 1's replica
        f0 = _rc("failover_total")
        reg = resilience.BreakerRegistry(threshold=1, cooldown_s=600.0,
                                         enabled=True)
        reg.record(0, ok=False)
        ok_fo, wall_fo, p99_fo = _drill(
            reg, HedgeConfig(enabled=False), "failover (primary dead)")
        reg.shutdown()
        failovers = _rc("failover_total") - f0

        # hedge drill: the primary lane of shard 0 answers slowly (the
        # in-process analog of the `delay` fault); hedges should win
        class _SlowPrimary:
            def __init__(self, inner, slow_wid, delay_s):
                self.inner, self.slow, self.d = inner, slow_wid, delay_s

            def answer_batch(self, wid, q, rc_, diff, via=None):
                if (wid if via is None else via) == self.slow:
                    time.sleep(self.d)
                return self.inner.answer_batch(wid, q, rc_, diff,
                                               via=via)

        hi0, hw0 = _rc("hedges_issued_total"), _rc("hedges_won_total")
        hbudget = float(os.environ.get("BENCH_REPL_HEDGE_BUDGET", 0.5))
        fe_h = ServingFrontend(
            rdc, _SlowPrimary(disp, 0, 0.05),
            sconf=ServeConfig(max_batch=64, max_wait_ms=1.0,
                              queue_depth=1024, cache_bytes=0,
                              deadline_ms=600_000.0),
            hconf=HedgeConfig(enabled=True, min_delay_ms=5.0,
                              budget=hbudget))
        fe_h.start()
        hpool = pool[:min(rn, 256)]
        t0 = time.perf_counter()
        hres = [fe_h.query(int(s), int(t), timeout=600)
                for s, t in hpool]
        wall_h = time.perf_counter() - t0
        fe_h.stop()
        time.sleep(0.3)          # drain loser primary threads
        hedges = _rc("hedges_issued_total") - hi0
        wins = _rc("hedges_won_total") - hw0
        repl_stats = {
            "repl_clean_queries_per_sec": round(ok_clean / wall_clean,
                                                1),
            "repl_clean_p99_ms": round(p99_clean, 3),
            "repl_failover_queries_per_sec": round(ok_fo / wall_fo, 1),
            "repl_failover_p99_ms": round(p99_fo, 3),
            "repl_failover_ok": int(ok_fo),
            "repl_failover_total": int(failovers),
            "repl_hedges_issued": int(hedges),
            "repl_hedges_won": int(wins),
            "repl_hedge_win_rate": round(wins / max(hedges, 1), 3),
            "repl_hedge_rate": round(fe_h.hedge.hedge_rate(), 3),
            "repl_hedged_queries_per_sec": round(
                sum(r.ok for r in hres) / wall_h, 1),
        }
        log(f"replication: clean "
            f"{repl_stats['repl_clean_queries_per_sec']:,.0f} q/s, "
            f"failover {repl_stats['repl_failover_queries_per_sec']:,.0f}"
            f" q/s ({failovers} failovers, {ok_fo}/{rn} ok), hedge "
            f"win rate {repl_stats['repl_hedge_win_rate']:.0%} at "
            f"hedge rate {repl_stats['repl_hedge_rate']:.2f}")
        shutil.rmtree(rdir, ignore_errors=True)

    # ---- reshard section: serve q/s + p99 through a LIVE worker join
    # (the elastic-membership dual-read window) vs the steady fleet.
    # A 2-worker world gains a third worker mid-load: begin opens the
    # window, catch_up adopts a shard, commit bumps the epoch — the
    # drill measures what the migration window costs the open workload.
    # BENCH_RESHARD=0 skips.
    reshard_stats = {}
    if os.environ.get("BENCH_RESHARD", "1") != "0":
        from distributed_oracle_search_tpu.data import (
            ensure_synth_dataset, read_scen,
        )
        from distributed_oracle_search_tpu.data.graph import Graph
        from distributed_oracle_search_tpu.models.cpd import (
            build_worker_shard, write_index_manifest,
        )
        from distributed_oracle_search_tpu.parallel import (
            membership as _fleet,
        )
        from distributed_oracle_search_tpu.serving import (
            EngineDispatcher, HedgeConfig, ServeConfig, ServingFrontend,
        )
        from distributed_oracle_search_tpu.transport.wire import (
            RuntimeConfig,
        )
        from distributed_oracle_search_tpu.utils.config import (
            ClusterConfig,
        )

        log("reshard (serve q/s through a live worker join)...")
        edir = tempfile.mkdtemp(prefix="bench-reshard-")
        epaths = ensure_synth_dataset(edir, width=24, height=18,
                                      n_queries=512, seed=37)
        econf = ClusterConfig(
            workers=["localhost"] * 2, partmethod="mod", partkey=2,
            outdir=os.path.join(edir, "index"),
            xy_file=epaths["xy"], scenfile=epaths["scen"],
            nfs=edir).validate()
        eg = Graph.from_xy(econf.xy_file)
        edc = DistributionController("mod", 2, 2, eg.n)
        for wid in range(2):
            build_worker_shard(eg, edc, wid, econf.outdir)
        write_index_manifest(econf.outdir, edc)
        equeries = read_scen(econf.scenfile)
        en = int(os.environ.get("BENCH_RESHARD_REQUESTS", 512))
        epool = equeries[np.arange(en) % len(equeries)]
        mc = _fleet.MembershipController(econf, edc, graph=eg)
        disp = EngineDispatcher(econf, graph=eg, dc=edc)
        for wid in range(2):     # warm the engines off the clock
            mine = equeries[edc.worker_of(equeries[:, 1]) == wid][:64]
            disp.answer_batch(wid, mine, RuntimeConfig(), "-")

        def _edrill(tag, during=None):
            """Closed-loop drill; ``during`` optionally runs the
            migration steps between the submit stream's halves so the
            window is genuinely live while queries flow."""
            fe = ServingFrontend(
                mc.dc_view(), disp,
                sconf=ServeConfig(max_batch=64, max_wait_ms=2.0,
                                  queue_depth=max(en, 1024),
                                  cache_bytes=0,
                                  deadline_ms=600_000.0),
                hconf=HedgeConfig(enabled=False), membership=mc)
            fe.start()
            t0 = time.perf_counter()
            submits, futs = [], []
            for i, (s, t) in enumerate(epool):
                if during is not None and i == len(epool) // 2:
                    during()
                submits.append(time.monotonic())
                futs.append(fe.submit(int(s), int(t)))
            res = [f.result(600) for f in futs]
            wall = time.perf_counter() - t0
            fe.stop()
            n_ok = sum(r.ok for r in res)
            lat = [(r.t_done - ts) * 1e3
                   for r, ts in zip(res, submits) if r.ok]
            p99 = float(np.percentile(lat, 99)) if lat else float("nan")
            log(f"  {tag}: {n_ok}/{en} ok in {wall:.2f}s "
                f"({n_ok / wall:,.0f} q/s, p99 {p99:.1f} ms)")
            return n_ok, wall, p99

        ok_st, wall_st, p99_st = _edrill("steady (epoch 0)")

        def _join_now():
            mig = mc.begin(mc.plan_join("localhost"), host="localhost")
            mc.catch_up(mig)
            mc.commit(mig)

        ok_mg, wall_mg, p99_mg = _edrill("migrating (live join)",
                                         during=_join_now)
        reshard_stats = {
            "reshard_steady_queries_per_sec": round(ok_st / wall_st, 1),
            "reshard_steady_p99_ms": round(p99_st, 3),
            "reshard_migrating_queries_per_sec": round(
                ok_mg / wall_mg, 1),
            "reshard_migrating_p99_ms": round(p99_mg, 3),
            "reshard_epoch_after": int(mc.epoch),
        }
        log(f"reshard: steady "
            f"{reshard_stats['reshard_steady_queries_per_sec']:,.0f} "
            f"q/s -> migrating "
            f"{reshard_stats['reshard_migrating_queries_per_sec']:,.0f}"
            f" q/s (epoch {mc.epoch} committed, {ok_mg}/{en} ok)")
        shutil.rmtree(edir, ignore_errors=True)

    # ---- traffic section: the live congestion plane (traffic/). A zipf
    # hotspot pool served steady on the base weights, then again while a
    # rush-hour segment replay swaps diff epochs UNDER the running
    # frontend — live-swap q/s, swap-stall p99, and the scoped-vs-full
    # invalidation hit rate (how much of the warm cache survives a swap
    # because its paths provably avoid the retimed corridor).
    # BENCH_TRAFFIC=0 skips.
    traffic_stats = {}
    if os.environ.get("BENCH_TRAFFIC", "1") != "0":
        from distributed_oracle_search_tpu.data import ensure_synth_dataset
        from distributed_oracle_search_tpu.data.graph import Graph
        from distributed_oracle_search_tpu.models.cpd import (
            build_worker_shard, write_index_manifest,
        )
        from distributed_oracle_search_tpu.obs import (
            metrics as _tmetrics,
        )
        from distributed_oracle_search_tpu.serving import (
            EngineDispatcher, HedgeConfig, ServeConfig, ServingFrontend,
        )
        from distributed_oracle_search_tpu.traffic import DiffEpochManager
        from distributed_oracle_search_tpu.traffic import (
            scenarios as tscen,
        )
        from distributed_oracle_search_tpu.transport.wire import (
            RuntimeConfig,
        )
        from distributed_oracle_search_tpu.utils.config import (
            ClusterConfig,
        )

        log("traffic (live epoch swaps over a zipf hotspot pool)...")
        tdir = tempfile.mkdtemp(prefix="bench-traffic-")
        tpaths = ensure_synth_dataset(tdir, width=24, height=18,
                                      n_queries=512, seed=41)
        tconf = ClusterConfig(
            workers=["localhost"] * 2, partmethod="mod", partkey=2,
            outdir=os.path.join(tdir, "index"),
            xy_file=tpaths["xy"], scenfile=tpaths["scen"],
            nfs=tdir).validate()
        tg = Graph.from_xy(tconf.xy_file)
        tdc = DistributionController("mod", 2, 2, tg.n)
        for wid in range(2):
            build_worker_shard(tg, tdc, wid, tconf.outdir)
        write_index_manifest(tconf.outdir, tdc)
        tn = int(os.environ.get("BENCH_TRAFFIC_REQUESTS", 2048))
        tpool = tscen.zipf_queries(tg.n, tn, seed=41)
        tdisp = EngineDispatcher(tconf, graph=tg, dc=tdc)
        stream_dir = os.path.join(tdir, "stream")
        tmgr = DiffEpochManager(stream_dir, poll_ms=25.0)
        # warm every micro-batch bucket shape off the clock with the
        # serve path's own knobs (sig_k rides the program key): the
        # live burst's post-swap misses arrive in odd-sized batches,
        # and a first-swap XLA compile must not masquerade as swap
        # stall — steady-state swaps are compile-free
        twconf = RuntimeConfig(sig_k=tmgr.sig_moves)
        for wid in range(2):
            mine = tpool[tdc.worker_of(tpool[:, 1]) == wid]
            for b in (1, 2, 4, 8, 16, 32, 64):
                if len(mine) >= b:
                    tdisp.answer_batch(wid, mine[:b], twconf, "-")
        tfe = ServingFrontend(
            tdc, tdisp,
            sconf=ServeConfig(max_batch=64, max_wait_ms=2.0,
                              queue_depth=max(tn, 2048),
                              deadline_ms=600_000.0).validate(),
            hconf=HedgeConfig(enabled=False), traffic=tmgr)
        tsnap0 = _tmetrics.REGISTRY.snapshot()["counters"]
        tfe.start()

        def _tburst(pool, during=()):
            """Closed-loop burst through the LIVE frontend; ``during``
            maps submit index -> hook (segment injection points), so
            swaps land while queries flow and the post-swap misses'
            stall shows up in this burst's p99."""
            t0 = time.perf_counter()
            submits, futs = [], []
            for i, (s, t) in enumerate(pool):
                hook = during.get(i) if during else None
                if hook is not None:
                    hook()
                submits.append(time.monotonic())
                futs.append(tfe.submit(int(s), int(t)))
            res = [f.result(600) for f in futs]
            wall = time.perf_counter() - t0
            lat = [(r.t_done - ts) * 1e3
                   for r, ts in zip(res, submits) if r.ok]
            return sum(r.ok for r in res), wall, lat

        try:
            _tburst(tpool)       # warm: engines compiled, cache filled
            ok_td, wall_td, lat_td = _tburst(tpool)   # steady, epoch 0
            p99_td = (float(np.percentile(lat_td, 99))
                      if lat_td else float("nan"))
            log(f"  steady (epoch 0): {ok_td}/{tn} ok "
                f"({ok_td / wall_td:,.0f} q/s, p99 {p99_td:.1f} ms)")

            # the same burst again, but rush-hour segments land at 1/3
            # and 2/3 of the stream (epoch 2 is the tent peak) and each
            # injection waits for the pump to APPLY the swap, so the
            # rest of the burst genuinely runs on the new fused diff —
            # re-keyed survivors hitting, affected entries re-answered
            trace = tscen.rush_hour_trace(tg, epochs=3, frac=0.02,
                                          peak=3.0, seed=41)

            def _inject(seg):
                def hook():
                    tscen.replay([seg], stream_dir)
                    deadline = time.monotonic() + 30.0
                    while (tfe._diff_epoch < seg["epoch"]
                           and time.monotonic() < deadline):
                        time.sleep(0.005)
                return hook

            ok_tl, wall_tl, lat_tl = _tburst(
                tpool, during={len(tpool) // 3: _inject(trace[0]),
                               (2 * len(tpool)) // 3: _inject(trace[1])})
            p99_tl = (float(np.percentile(lat_tl, 99))
                      if lat_tl else float("nan"))
            swapped = int(tfe._diff_epoch)
            log(f"  live swap: {ok_tl}/{tn} ok "
                f"({ok_tl / wall_tl:,.0f} q/s, p99 {p99_tl:.1f} ms, "
                f"{swapped} epoch(s) applied)")

            # scoped-invalidation hit rate straight from the swap
            # passes' own accounting: survivors re-keyed / entries
            # examined. (NOT a post-swap resubmission probe — the live
            # burst re-caches the hot pool under the new epoch, so a
            # probe would read near-1.0 even with scoped invalidation
            # fully broken.)
            tsnap = _tmetrics.REGISTRY.snapshot()["counters"]

            def _tdelta(name):
                return int(tsnap.get(name, 0)) - int(tsnap0.get(name, 0))

            kept = _tdelta("serve_cache_rekeyed_total")
            sdrop = _tdelta("serve_cache_invalidated_scoped_total")
            traffic_stats = {
                "traffic_steady_queries_per_sec": round(
                    ok_td / wall_td, 1),
                "traffic_steady_p99_ms": round(p99_td, 3),
                "traffic_live_swap_queries_per_sec": round(
                    ok_tl / wall_tl, 1),
                "traffic_swap_stall_p99_ms": round(p99_tl, 3),
                "traffic_epochs_swapped": swapped,
                "traffic_scoped_hit_rate": round(
                    kept / (kept + sdrop), 4) if kept + sdrop else 0.0,
                "traffic_invalidated_scoped": sdrop,
                "traffic_invalidated_full": _tdelta(
                    "serve_cache_invalidated_full_total"),
            }
            log(f"traffic: steady "
                f"{traffic_stats['traffic_steady_queries_per_sec']:,.0f}"
                f" q/s -> live-swap "
                f"{traffic_stats['traffic_live_swap_queries_per_sec']:,.0f}"
                f" q/s, scoped hit rate "
                f"{traffic_stats['traffic_scoped_hit_rate']:.0%}")
        finally:
            tfe.stop()
        shutil.rmtree(tdir, ignore_errors=True)

    control_stats = {}
    if os.environ.get("BENCH_CONTROL", "1") != "0":
        from distributed_oracle_search_tpu.control import (
            ControlConfig, ControlDaemon,
        )
        from distributed_oracle_search_tpu.data import ensure_synth_dataset
        from distributed_oracle_search_tpu.data.graph import Graph
        from distributed_oracle_search_tpu.models.cpd import (
            build_worker_shard, write_index_manifest,
        )
        from distributed_oracle_search_tpu.serving import (
            DispatchError, EngineDispatcher, HedgeConfig, ServeConfig,
            ServingFrontend,
        )
        from distributed_oracle_search_tpu.traffic import DiffEpochManager
        from distributed_oracle_search_tpu.traffic import (
            scenarios as cscen,
        )
        from distributed_oracle_search_tpu.transport.resilience import (
            BreakerRegistry,
        )
        from distributed_oracle_search_tpu.transport.wire import (
            HealthStatus, RuntimeConfig,
        )
        from distributed_oracle_search_tpu.utils.config import (
            ClusterConfig,
        )
        from distributed_oracle_search_tpu.worker.supervisor import (
            WorkerSupervisor,
        )

        log("closed-loop control (rush-hour + worker kill, policy on "
            "vs off)...")
        cdir = tempfile.mkdtemp(prefix="bench-control-")
        cpaths = ensure_synth_dataset(cdir, width=20, height=15,
                                      n_queries=256, seed=47)
        cconf = ClusterConfig(
            workers=["localhost"] * 2, partmethod="mod", partkey=2,
            outdir=os.path.join(cdir, "index"),
            xy_file=cpaths["xy"], scenfile=cpaths["scen"],
            nfs=cdir).validate()
        cg = Graph.from_xy(cconf.xy_file)
        cdc = DistributionController("mod", 2, 2, cg.n)
        for wid in range(2):
            build_worker_shard(cg, cdc, wid, cconf.outdir)
        write_index_manifest(cconf.outdir, cdc)
        cn = int(os.environ.get("BENCH_CONTROL_REQUESTS", 1200))
        crng = np.random.default_rng(47)
        cpool = cscen.zipf_queries(cg.n, cn, seed=47)
        ctrace = cscen.rush_hour_trace(cg, epochs=2, frac=0.02,
                                       peak=3.0, seed=47)

        class _ThreadProc:
            """Popen shape over an in-process worker slot, so the real
            WorkerSupervisor (and its kick/backoff machinery) can
            supervise the incident without subprocess costs."""

            _next_pid = [1]

            def __init__(self):
                self.dead = False
                self.returncode = None
                self.pid = 90_000 + self._next_pid[0]
                self._next_pid[0] += 1

            def poll(self):
                if self.dead:
                    self.returncode = 0
                    return 0
                return None

            def wait(self, timeout=None):
                if self.dead:
                    return 0
                raise subprocess.TimeoutExpired("threadproc",
                                                timeout or 0)

            def terminate(self):
                self.dead = True

            def kill(self):
                self.dead = True

        class _GatedDispatch:
            """EngineDispatcher behind a per-worker liveness gate: a
            dead worker's sends hang (the dead-FIFO analog) until a
            send-timeout, so the un-policed fleet pays the realistic
            price for routing at a corpse."""

            def __init__(self, inner, alive, hang_s=0.6):
                self.inner = inner
                self.alive = alive
                self.hang_s = hang_s

            def answer_batch(self, wid, q, rconf, diff, via=None):
                w = wid if via is None else via
                if not self.alive.get(w, True):
                    deadline = time.monotonic() + self.hang_s
                    while not self.alive.get(w, True):
                        if time.monotonic() >= deadline:
                            raise DispatchError(
                                f"worker {w} unreachable")
                        time.sleep(0.01)
                return self.inner.answer_batch(wid, q, rconf, diff,
                                               via=via)

        def _control_run(policy_on):
            alive = {0: True, 1: True}
            procs = {}

            def spawn(w):
                alive[w.wid] = True
                procs[w.wid] = _ThreadProc()
                return procs[w.wid]

            def probe(w):
                if alive.get(w.wid) and not w.proc.dead:
                    return HealthStatus(ok=True, wid=w.wid)
                return None

            sup = WorkerSupervisor(cconf, conf_path=None,
                                   spawn_fn=spawn, probe_fn=probe,
                                   ping_interval_s=0.1,
                                   backoff_base_s=6.0,
                                   backoff_cap_s=8.0)
            reg = BreakerRegistry(threshold=3, cooldown_s=1.0,
                                  enabled=True)
            stream = os.path.join(
                cdir, f"stream-{'on' if policy_on else 'off'}")
            cmgr = DiffEpochManager(stream, poll_ms=25.0)
            cdisp = _GatedDispatch(
                EngineDispatcher(cconf, graph=cg, dc=cdc), alive)
            fe = ServingFrontend(
                cdc, cdisp,
                sconf=ServeConfig(max_batch=32, max_wait_ms=2.0,
                                  queue_depth=max(cn, 2048),
                                  deadline_ms=2000.0).validate(),
                hconf=HedgeConfig(enabled=False), traffic=cmgr,
                registry=reg, breaker_key=lambda wid: wid)
            daemon = None
            if policy_on:
                daemon = ControlDaemon(
                    ControlConfig(enabled=True, interval_s=0.1,
                                  cooldown_s=0.5, hold_ticks=1,
                                  clean_probes=1, unhealthy_pings=2),
                    supervisor=sup, registry=reg, frontend=fe,
                    breaker_key=lambda wid: wid,
                    replicate_fn=lambda shard: None,
                    probe_fn=lambda wid: bool(alive.get(wid)))
            sup.start(wait_ready_s=10)
            fe.start()
            if daemon is not None:
                daemon.start()
            kill_at = cn // 3
            shift_at = (2 * cn) // 3
            t_kill = None
            try:
                # warm: engines compiled, shapes resident
                for f in [fe.submit(int(s), int(t))
                          for s, t in cpool[:64]]:
                    f.result(60)
                submits, futs = [], []
                for i, (s, t) in enumerate(cpool):
                    if i == kill_at:
                        # the incident: worker 1 dies mid-serve
                        t_kill = time.monotonic()
                        procs[1].dead = True
                        alive[1] = False
                    if i == shift_at:
                        # the hotspot shift: a rush-hour segment lands
                        # and the pump swaps the fused diff live
                        cscen.replay([ctrace[0]], stream)
                    submits.append(time.monotonic())
                    futs.append(fe.submit(int(s), int(t)))
                    time.sleep(0.003)
                res = [f.result(60) for f in futs]
                t_end = time.monotonic()
            finally:
                if daemon is not None:
                    daemon.stop()
                fe.stop()
                sup.stop()
                reg.shutdown()
            ok = [(r, ts) for r, ts in zip(res, submits) if r.ok]
            shed_rate = 1.0 - len(ok) / len(res)
            lat = [(r.t_done - ts) * 1e3 for r, ts in ok]
            p99 = float(np.percentile(lat, 99)) if lat else float("nan")
            # recovery: first OK non-cached answer to a query SUBMITTED
            # after the kill and routed to the killed worker's shard —
            # in-flight stragglers and cache hits don't prove the
            # worker came back
            healed = [r.t_done for r, ts in ok
                      if t_kill is not None and ts > t_kill
                      and not r.cached
                      and int(cdc.worker_of(np.asarray([r.t]))[0]) == 1]
            # no healed sample within the burst → report the observed
            # outage as a floor (the fleet never recovered on camera)
            recover = (min(healed) - t_kill) if healed \
                else (t_end - t_kill if t_kill else 0.0)
            return shed_rate, recover, p99

        shed_off, rec_off, p99_off = _control_run(policy_on=False)
        log(f"  policy OFF: shed {shed_off:.1%}, recover "
            f"{rec_off:.2f}s, p99 {p99_off:.1f} ms")
        shed_on, rec_on, p99_on = _control_run(policy_on=True)
        log(f"  policy ON:  shed {shed_on:.1%}, recover "
            f"{rec_on:.2f}s, p99 {p99_on:.1f} ms")
        control_stats = {
            "control_shed_rate": round(shed_on, 4),
            "control_recover_seconds": round(rec_on, 3),
            "control_p99_ms": round(p99_on, 3),
            "control_off_shed_rate": round(shed_off, 4),
            "control_off_recover_seconds": round(rec_off, 3),
            "control_off_p99_ms": round(p99_off, 3),
        }
        shutil.rmtree(cdir, ignore_errors=True)

    integrity_stats = {}
    if os.environ.get("BENCH_INTEGRITY", "1") != "0":
        from distributed_oracle_search_tpu.data import ensure_synth_dataset
        from distributed_oracle_search_tpu.data.graph import Graph
        from distributed_oracle_search_tpu.integrity.audit import (
            AnswerAuditor, make_reference_fn,
        )
        from distributed_oracle_search_tpu.integrity.scrub import (
            TableScrubber,
        )
        from distributed_oracle_search_tpu.models.cpd import (
            build_worker_shard, write_index_manifest,
        )
        from distributed_oracle_search_tpu.parallel.partition import (
            DistributionController,
        )
        from distributed_oracle_search_tpu.serving import (
            EngineDispatcher, HedgeConfig, ServeConfig, ServingFrontend,
        )
        from distributed_oracle_search_tpu.testing import faults
        from distributed_oracle_search_tpu.traffic import (
            scenarios as iscen,
        )
        from distributed_oracle_search_tpu.transport.wire import (
            RuntimeConfig,
        )
        from distributed_oracle_search_tpu.utils.config import (
            ClusterConfig,
        )

        log("answer integrity (audit overhead at 0/1/10 per mille, "
            "scrub overhead, corrupt-resident + corrupt-answer "
            "drills)...")
        igdir = tempfile.mkdtemp(prefix="bench-integrity-")
        igpaths = ensure_synth_dataset(igdir, width=20, height=15,
                                       n_queries=256, seed=53)
        igconf = ClusterConfig(
            workers=["localhost"] * 2, partmethod="mod", partkey=2,
            outdir=os.path.join(igdir, "index"),
            xy_file=igpaths["xy"], scenfile=igpaths["scen"],
            nfs=igdir).validate()
        ig_g = Graph.from_xy(igconf.xy_file)
        ig_dc = DistributionController("mod", 2, 2, ig_g.n)
        for wid in range(2):
            build_worker_shard(ig_g, ig_dc, wid, igconf.outdir)
        write_index_manifest(igconf.outdir, ig_dc)
        ig_n = int(os.environ.get("BENCH_INTEGRITY_REQUESTS", 2000))
        ig_pool = iscen.zipf_queries(ig_g.n, ig_n, seed=53)

        def _integrity_run(audit_pm=0, scrub=False, answer_fp=False,
                           pool=None):
            """One timed serving burst; the cache is off so every
            request pays a real dispatch (an audit/scrub overhead
            hidden behind cache hits would be a meaningless number).
            Returns (q/s, ok results, audit divergence count)."""
            pool = ig_pool if pool is None else pool
            igdisp = EngineDispatcher(igconf, graph=ig_g, dc=ig_dc)
            igfe = ServingFrontend(
                ig_dc, igdisp,
                sconf=ServeConfig(max_batch=32, max_wait_ms=2.0,
                                  queue_depth=max(ig_n, 2048),
                                  deadline_ms=5000.0,
                                  cache_bytes=0).validate(),
                rconf=RuntimeConfig(answer_fp=answer_fp),
                hconf=HedgeConfig(enabled=False))
            auditor = scrubber = None
            if audit_pm:
                auditor = AnswerAuditor(
                    igdisp, audit_pm,
                    reference_fn=make_reference_fn(ig_g),
                    queue_max=1024)
                igfe.auditor = auditor
            igfe.start()
            try:
                # warm outside the timed window: engines built,
                # programs compiled
                for f in [igfe.submit(int(s), int(t))
                          for s, t in pool[:64]]:
                    f.result(60)
                if scrub:
                    scrubber = TableScrubber(
                        lambda: list(igdisp._engines.values()), 0.05)
                    scrubber.start()
                t0 = time.monotonic()
                futs = [igfe.submit(int(s), int(t)) for s, t in pool]
                res = [f.result(60) for f in futs]
                wall = time.monotonic() - t0
                divergence = 0
                if auditor is not None:
                    # drain the audit queue so divergences booked
                    # off-path are all counted
                    end = time.monotonic() + 60
                    while (not auditor._q.empty()
                           and time.monotonic() < end):
                        time.sleep(0.02)
                    divergence = sum(auditor.snapshot().values())
            finally:
                if scrubber is not None:
                    scrubber.stop()
                if auditor is not None:
                    auditor.stop()
                igfe.stop()
            ok = [r for r in res if r.ok]
            return len(ok) / wall, ok, divergence

        base_qps, base_ok, _ = _integrity_run()
        truth = {(r.s, r.t): (int(r.cost), int(r.plen))
                 for r in base_ok}
        audit1_qps, _, _ = _integrity_run(audit_pm=1)
        audit10_qps, _, _ = _integrity_run(audit_pm=10)
        scrub_qps, _, _ = _integrity_run(scrub=True)
        # clean-run audit at full rate: every batch re-executed on the
        # CPU reference lane — ANY divergence here is a real bug
        _, _, clean_div = _integrity_run(audit_pm=1000,
                                         pool=ig_pool[:400])

        # corrupt-answer drill: bits flip in reply payloads after the
        # fingerprint is computed; the dispatcher's verifier must
        # suppress every one — served answers stay truth-identical
        os.environ["DOS_FAULTS"] = "corrupt-answer;times=20"
        faults.reset()
        try:
            _, drill_ok, _ = _integrity_run(answer_fp=True)
        finally:
            del os.environ["DOS_FAULTS"]
            faults.reset()
        wrong = sum(1 for r in drill_ok
                    if (r.s, r.t) in truth
                    and truth[(r.s, r.t)] != (int(r.cost),
                                              int(r.plen)))

        # corrupt-resident drill: flip rows in one engine's RESIDENT
        # table behind serving's back; detection latency is flip ->
        # the scrubber's corrupt-block booking (+ rebind from disk)
        igdisp = EngineDispatcher(igconf, graph=ig_g, dc=ig_dc)
        igfe = ServingFrontend(
            ig_dc, igdisp,
            sconf=ServeConfig(max_batch=32, max_wait_ms=2.0,
                              queue_depth=2048, deadline_ms=5000.0,
                              cache_bytes=0).validate(),
            hconf=HedgeConfig(enabled=False))
        igfe.start()
        detect_s = float("nan")
        try:
            for f in [igfe.submit(int(s), int(t))
                      for s, t in ig_pool[:64]]:
                f.result(60)
            ig_eng = next(iter(igdisp._engines.values()))
            bad = np.array(np.asarray(ig_eng.fm), np.int8, copy=True)
            bad[0, :] = np.where(bad[0, :] <= 0, 1, 0)
            ig_eng.fm = bad
            igscrub = TableScrubber(
                lambda: list(igdisp._engines.values()), 0.05)
            t_flip = time.monotonic()
            igscrub.start()
            try:
                while time.monotonic() - t_flip < 30:
                    if igscrub.corrupt_blocks > 0:
                        detect_s = time.monotonic() - t_flip
                        break
                    time.sleep(0.01)
            finally:
                igscrub.stop()
        finally:
            igfe.stop()

        integrity_stats = {
            "integrity_base_queries_per_sec": round(base_qps, 1),
            "integrity_audit1_queries_per_sec": round(audit1_qps, 1),
            "integrity_audit10_queries_per_sec": round(audit10_qps, 1),
            "integrity_scrub_queries_per_sec": round(scrub_qps, 1),
            "integrity_audit_overhead_frac": round(
                1.0 - audit1_qps / base_qps, 4),
            "integrity_scrub_overhead_frac": round(
                1.0 - scrub_qps / base_qps, 4),
            "integrity_audit_divergence": int(clean_div),
            "integrity_wrong_answers_served": int(wrong),
            "integrity_detect_seconds": round(detect_s, 3),
        }
        log(f"  base {base_qps:,.0f} q/s; audit 1 per mille "
            f"{audit1_qps:,.0f} q/s "
            f"({integrity_stats['integrity_audit_overhead_frac']:+.1%}"
            f" overhead); scrub on {scrub_qps:,.0f} q/s; clean-run "
            f"divergences {clean_div}; corrupted answers served "
            f"{wrong}; resident corruption detected in "
            f"{detect_s:.2f}s")
        shutil.rmtree(igdir, ignore_errors=True)

    target_time = 1.0  # north star: whole scenario < 1 s (BASELINE.json)
    detail = {
        "graph_nodes": g.n,
        "graph_edges": g.m,
        "n_queries": n_queries,
        "scenario_seconds": round(t_scen.interval, 4),
        "warmup_seconds": warmups,
        "diff_queries_per_sec": round(n_queries / t_diff.interval, 1),
        "dist_queries_per_sec": round(n_queries / t_dist.interval, 1),
        **cpu_stats,
        **table_stats,
        "cpd_build_seconds": round(t_build_s, 2),
        "cpd_rows_per_sec": round(rows_per_s, 1),
        **verify_stats,
        "roofline": {
            "kernel_seconds": round(t_kern_s, 4),
            "peak_gather_meps": round(peak_gather / 1e6, 1),
            "walk_useful_gather_meps": round(achieved_gather / 1e6, 1),
            "walk_issued_gather_meps": round(issued_gather / 1e6, 1),
            # issued/peak: how close the bucketed walk's issue rate
            # comes to a full-width dependent-gather chain. The
            # bucket tuning trades THIS DOWN for fewer wasted lanes
            # (each bucket exits at its own max length), so read it
            # WITH issue_efficiency (useful/issued, the waste
            # metric) — narrower buckets raise efficiency and total
            # speed while lowering raw issue rate
            "walk_gather_utilization": round(
                issued_gather / peak_gather, 3),
            "walk_issue_efficiency": round(
                achieved_gather / issued_gather, 3),
            # non-pad lanes / issued lanes: the padding-proof figure
            # for kernel-vs-kernel roofline comparisons (see the
            # honest-lane-accounting note at its computation)
            "walk_useful_lane_fraction": round(useful_lane_fraction, 3),
            "hbm_stream_gbps": round(hbm_bw / 1e9, 1),
            # XLA cost/memory analysis of the walk program + the derived
            # achieved-vs-peak gather-bandwidth figure (obs.device)
            **({"walk_flops": walk_costs.get("flops"),
                "walk_bytes_accessed": walk_costs.get("bytes_accessed"),
                "walk_hbm_bytes": walk_costs.get("hbm_bytes"),
                "walk_achieved_gbps": walk_costs.get("achieved_gbps"),
                "walk_hbm_bw_utilization":
                    walk_costs.get("hbm_bw_utilization")}
               if walk_costs else {}),
            # fused Pallas walk kernel, keyed next to the XLA figures
            # (empty off-TPU / under BENCH_PALLAS=0)
            **pallas_roof,
        },
        **scale_stats,
        **road_stats,
        **comp_stats,
        **delta_stats,
        **weak_stats,
        **mesh_stats,
        **multichip_stats,
        **serve_stats,
        **rpc_stats,
        **gateway_stats,
        **gateway_ha_stats,
        **telemetry_stats,
        **repl_stats,
        **reshard_stats,
        **traffic_stats,
        **control_stats,
        **integrity_stats,
        "devices": len(devices),
        "platform": devices[0].platform,
    }
    # structured internals: the obs registry's counters + per-phase
    # histograms accumulated by whatever instrumented paths this run
    # exercised (detail file only — the stdout line stays compact)
    from distributed_oracle_search_tpu.obs import metrics as obs_metrics
    detail["obs"] = obs_metrics.REGISTRY.snapshot()
    # per-program-key XLA cost/memory analyses accumulated by every
    # engine this run compiled programs in (obs.device): FLOPs, bytes
    # accessed, HBM footprint per (alg, shape, knobs) key
    detail["device_costs"] = obs_device.snapshot()
    payload = {
        "metric": "scenario_queries_per_sec",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(target_time / t_scen.interval, 3),
        "detail": detail,
    }
    # full per-section detail: to a sidecar file + stderr. The driver of
    # record keeps only the LAST ~2000 stdout chars and parses the final
    # line — r04's fat single line overflowed that window and the record
    # came back unparseable (BENCH_r04.json "parsed": null)
    here = os.path.dirname(os.path.abspath(__file__))
    detail_path = os.path.join(here, "BENCH_DETAIL.json")
    with open(detail_path, "w") as f:
        json.dump(payload, f, indent=1)
    log("full detail -> " + detail_path)
    log("full detail: " + json.dumps(payload))

    headline_keys = (
        "tpu_build_parity_cores", "tpu_query_speedup",
        "tpu_dist_bulk_speedup", "table_prepare_seconds",
        "table_multi_amortization", "tpu_astar_queries_per_sec",
        "scale_build_rows_per_sec", "scale_build_parity_cores",
        "scale_stream_queries_per_sec", "scale_stream_wire_mb",
        "scale_stream_mb", "scale_stream_warm_queries_per_sec",
        "scale_tpu_stream_speedup", "scale_tpu_resident_speedup",
        "road_build_parity_cores", "road_tpu_build_rows_per_sec",
        "road_stream_queries_per_sec", "road_resident_queries_per_sec",
        "road_tpu_resident_speedup", "road_multidiff_fused_speedup",
        "cpd_resident_bytes_ratio", "compressed_walk_queries_per_sec",
        "compressed_vs_raw_walk_ratio",
        "build_delta_vs_full_ratio", "build_delta_rows_per_sec",
        "shard_strong_scaling_rows_per_sec",
        "shard_strong_scaling_rows_per_sec_w1",
        "shard_strong_scaling_rows_per_sec_w8",
        "shard_strong_scaling_overhead_w8_seconds",
        "mesh_build_rows_per_sec_d8", "mesh_walk_queries_per_sec_d8",
        "mesh_mat_rows_per_sec_d8", "multichip_smoke_ok",
        "serve_queries_per_sec", "serve_p99_ms",
        "serve_cache_hit_rate", "serve_mean_batch_fill",
        "serve_rpc_vs_fifo_dispatch_ratio", "serve_rpc_dispatch_ms",
        "serve_fifo_dispatch_ms", "serve_rpc_p99_ms",
        "serve_fifo_p99_ms",
        "telemetry_publish_p99_ms", "telemetry_publish_overhead_frac",
        "telemetry_head_ingest_per_sec",
        "traffic_live_swap_queries_per_sec", "traffic_swap_stall_p99_ms",
        "traffic_scoped_hit_rate",
        "control_shed_rate", "control_off_shed_rate",
        "control_recover_seconds", "control_off_recover_seconds",
        "integrity_audit_overhead_frac",
        "integrity_wrong_answers_served", "integrity_detect_seconds",
        "devices", "platform",
    )
    headline = {k: detail[k] for k in headline_keys if k in detail}
    headline["walk_gather_utilization"] = \
        detail["roofline"]["walk_gather_utilization"]
    headline["walk_issue_efficiency"] = \
        detail["roofline"]["walk_issue_efficiency"]
    headline["walk_useful_lane_fraction"] = \
        detail["roofline"]["walk_useful_lane_fraction"]
    for k in ("walk_pallas_queries_per_sec", "walk_pallas_speedup"):
        if k in detail["roofline"]:
            headline[k] = detail["roofline"][k]
    line = json.dumps({
        "metric": payload["metric"],
        "value": payload["value"],
        "unit": payload["unit"],
        "vs_baseline": payload["vs_baseline"],
        "detail_file": "BENCH_DETAIL.json",
        "headline": headline,
    })
    # hard gate on the driver's tail window (~2000 chars): a line that
    # outgrows it silently destroys the round's number of record
    assert len(line) < 1800, f"final bench line too long: {len(line)}"
    print(line)


if __name__ == "__main__":
    main()
