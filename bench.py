"""Headline benchmark: whole-scenario query throughput on the CPD oracle.

Mirrors the reference's headline workload (BASELINE.md): build the CPD for a
city-scale road network, then answer an entire scenario file of s–t queries.
The north-star target is "every query in full.scen answered in < 1 s"
(BASELINE.json): ``vs_baseline`` reports target_time / measured_time for the
scenario phase, so > 1.0 means beating the target.

The reference's own data files are absent from its snapshot, so the workload
is a deterministic synthetic city of comparable structure (two-way street
grid + arterials; see ``data/synth.py``). Scale via env:

    BENCH_WIDTH/BENCH_HEIGHT  grid size        (default 96x96 ≈ 9.2k nodes)
    BENCH_QUERIES             scenario size    (default 50_000)
    BENCH_CHUNK               build batch rows (default 512)

Prints exactly ONE JSON line to stdout; progress goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import numpy as np

    try:  # persistent compile cache: repeated bench runs skip XLA compiles
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/jax_bench"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # pragma: no cover - cache is best-effort
        log(f"compilation cache unavailable: {e}")

    from distributed_oracle_search_tpu.data import synth_city_graph, synth_scenario
    from distributed_oracle_search_tpu.models.cpd import CPDOracle
    from distributed_oracle_search_tpu.parallel import DistributionController
    from distributed_oracle_search_tpu.parallel.mesh import make_mesh
    from distributed_oracle_search_tpu.utils import Timer

    width = int(os.environ.get("BENCH_WIDTH", 96))
    height = int(os.environ.get("BENCH_HEIGHT", 96))
    n_queries = int(os.environ.get("BENCH_QUERIES", 50_000))
    chunk = int(os.environ.get("BENCH_CHUNK", 512))

    devices = jax.devices()
    log(f"devices: {devices}")
    n_workers = len(devices)

    with Timer() as t_gen:
        g = synth_city_graph(width, height, seed=0)
        queries = synth_scenario(g.n, n_queries, seed=1)
    log(f"graph n={g.n} m={g.m} K={g.max_out_degree}; "
        f"{n_queries} queries; gen {t_gen}")

    dc = DistributionController("tpu", None, n_workers, g.n)
    mesh = make_mesh(n_workers=n_workers)
    oracle = CPDOracle(g, dc, mesh=mesh)

    with Timer() as t_build:
        oracle.build(chunk=chunk, store_dists=True)
        jax.block_until_ready(oracle.fm)
    rows_per_s = g.n / t_build.interval
    log(f"CPD build: {t_build} ({rows_per_s:,.0f} target rows/s, "
        f"{g.n * g.n / t_build.interval / 1e9:.2f} G entries/s)")

    # congestion diff for the perturbed round (reference: one round/diff)
    from distributed_oracle_search_tpu.data import synth_diff
    dsrc, ddst, dw = synth_diff(g, frac=0.1, seed=2)
    w_diff = g.weights_with_diff((dsrc, ddst, dw))

    bench_table = os.environ.get("BENCH_TABLE", "1") != "0"

    # warm-up at the full scenario shape: compiles each query program once,
    # like the reference's resident fifo_auto loading before the campaign
    with Timer() as t_compile:
        oracle.query(queries)
        oracle.query(queries, w_query=w_diff)
        oracle.query_dist(queries)
        if bench_table:
            warm = oracle.prepare_weights(w_diff)
            oracle.query_table(warm, queries)
            jax.block_until_ready(warm[0])
            del warm
    log(f"query warm-up (compile): {t_compile}")

    with Timer() as t_scen:
        cost, plen, finished = oracle.query(queries)
    n_fin = int(finished.sum())
    qps = n_queries / t_scen.interval
    log(f"walk free-flow: {n_queries} in {t_scen} -> {qps:,.0f} q/s; "
        f"finished {n_fin}/{n_queries}, mean plen {plen.mean():.1f}")
    assert n_fin == n_queries, "benchmark correctness gate failed"

    with Timer() as t_diff:
        cost_d, plen_d, fin_d = oracle.query(queries, w_query=w_diff)
    assert int(fin_d.sum()) == n_queries
    assert (cost_d >= cost).all(), "diffed costs must dominate free flow"
    log(f"walk diffed:   {n_queries} in {t_diff} -> "
        f"{n_queries / t_diff.interval:,.0f} q/s")

    with Timer() as t_dist:
        cost_g, fin_g = oracle.query_dist(queries)
    assert (cost_g == cost).all(), "dist fast path must match the walk"
    log(f"dist gather:   {n_queries} in {t_dist} -> "
        f"{n_queries / t_dist.interval:,.0f} q/s")

    # pointer-doubling amortization path: whole-shard cost tables for the
    # DIFFED weights, then gather-speed answers. Costs O(R*N*log L)
    # gathers up front — the >1M-query trade (BASELINE.md configs[4]).
    # BENCH_TABLE=0 skips it for quick runs.
    table_stats = {}
    if bench_table:
        with Timer() as t_prep:
            tables = oracle.prepare_weights(w_diff)
            jax.block_until_ready(tables[0])
        with Timer() as t_tab:
            cost_t, plen_t, fin_t = oracle.query_table(tables, queries)
        assert (cost_t == cost_d).all(), \
            "table path must match the diff walk"
        assert (plen_t == plen_d).all() and (fin_t == fin_d).all()
        log(f"diff tables:   prepare {t_prep}; {n_queries} in {t_tab} -> "
            f"{n_queries / t_tab.interval:,.0f} q/s")
        table_stats = {
            "table_prepare_seconds": round(t_prep.interval, 3),
            "table_queries_per_sec": round(n_queries / t_tab.interval, 1),
        }

    target_time = 1.0  # north star: whole scenario < 1 s (BASELINE.json)
    print(json.dumps({
        "metric": "scenario_queries_per_sec",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(target_time / t_scen.interval, 3),
        "detail": {
            "graph_nodes": g.n,
            "graph_edges": g.m,
            "n_queries": n_queries,
            "scenario_seconds": round(t_scen.interval, 4),
            "diff_queries_per_sec": round(n_queries / t_diff.interval, 1),
            "dist_queries_per_sec": round(n_queries / t_dist.interval, 1),
            **table_stats,
            "cpd_build_seconds": round(t_build.interval, 2),
            "cpd_rows_per_sec": round(rows_per_s, 1),
            "devices": len(devices),
            "platform": devices[0].platform,
        },
    }))


if __name__ == "__main__":
    main()
